"""Serving-path chaos suite (ISSUE 7).

Every claim the replicated serving tier makes is proven here under
injected faults (``MXNET_FI_SERVE_*``, runtime-togglable), counter-
verified through ``serving.replica.*``:

- kill a replica under concurrent traffic → ZERO failed client requests
  (failover re-dispatch absorbs it; only latency moves);
- all replicas down → fast typed 503-mapped errors, never hangs, within
  2x the request deadline;
- the replica recovers → traffic returns through the half-open probe;
- a hung replica is timed out by the watchdog and the batch fails over;
- hedging duplicates a slow batch to a second replica;
- a reload failure on one replica ejects it instead of poisoning the
  pool;
- the request path performs ZERO XLA compiles across failover and hedged
  re-dispatch, and per-bucket outputs are bitwise identical regardless of
  which replica served the batch;
- the batcher worker survives unhandled errors (typed failure + restart)
  and admission degrades proportionally with healthy capacity.

Runs on CPU with virtual devices (conftest forces
``--xla_force_host_platform_device_count=8``).
"""

import contextlib
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faultinject
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (DynamicBatcher, ModelServer,
                               NoHealthyReplicas, ServerOverloaded,
                               ServingConfig, WorkerCrashed)

pytestmark = [pytest.mark.chaos, pytest.mark.sanitize]


@pytest.fixture(autouse=True)
def _clean_serve_faults(monkeypatch):
    """No serving fault leaks across tests; ordinals rewound."""
    faultinject.reset()
    for k in ("MXNET_FI_SERVE_RAISE_REPLICA", "MXNET_FI_SERVE_LATENCY_MS",
              "MXNET_FI_SERVE_LATENCY_REPLICA", "MXNET_FI_SERVE_FAIL_EVERY",
              "MXNET_FI_SERVE_RELOAD_CORRUPT"):
        monkeypatch.delenv(k, raising=False)
    yield
    faultinject.reset()


def _mlp_params(seed=0, num_classes=4, scale=1.0):
    from mxnet_tpu import models

    sym = models.mlp(num_classes=num_classes)
    arg_shapes, _, _ = sym.infer_shape(data=(1, 6), softmax_label=(1,))
    rng = np.random.RandomState(seed)
    params = {}
    for n, s in zip(sym.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        params[n] = mx.nd.array(
            (scale * rng.randn(*s)).astype(np.float32))
    return sym, params


@contextlib.contextmanager
def _server(replicas=2, buckets=(1, 4), started=True, seed=0, **cfg):
    cfg.setdefault("max_delay_ms", 1.0)
    cfg.setdefault("queue_depth", 128)
    sym, params = _mlp_params(seed=seed)
    srv = ModelServer(
        sym, params, {"data": (6,)},
        config=ServingConfig(buckets=buckets, replicas=replicas, **cfg))
    try:
        if started:
            srv.start()
        yield srv
    finally:
        srv.close()


def _x(i=0):
    rng = np.random.RandomState(100 + i)
    return rng.uniform(-1, 1, (6,)).astype(np.float32)


def _delta(name):
    c = mx.telemetry.counter(name)
    v0 = c.value
    return lambda: c.value - v0


def test_replica_pool_construction_and_routing():
    """Two replicas bind distinct devices, each with the full bucket set
    sharing device arrays per replica; traffic spreads across both."""
    with _server(replicas=2, max_delay_ms=0.0) as srv:
        assert len(srv.replicas) == 2
        devs = {r.device() for r in srv.replicas}
        assert len(devs) == 2, f"replicas share a device: {devs}"
        for rep in srv.replicas:
            assert sorted(rep.predictors) == [1, 4]
        # concurrent traffic must actually use both replicas
        threads = []
        for i in range(24):
            t = threading.Thread(
                target=lambda i=i: srv.predict(_x(i), timeout=30))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        served = [r.batches for r in srv.replicas]
        assert all(b > 0 for b in served), (
            f"least-loaded routing starved a replica: {served}")
        assert mx.telemetry.gauge("serving.replica.healthy").value == 2


def test_replica_kill_under_traffic_zero_client_errors(monkeypatch):
    """Kill replica 0 under >= 32 concurrent in-flight requests: every
    request completes (failover), the breaker opens, the healthy gauge
    drops to 1 — zero client-visible errors."""
    failover = _delta("serving.replica.failover")
    opened = _delta("serving.replica.open")
    with _server(replicas=2, cb_probe_ms=60_000) as srv:
        failures = []
        done = []  # list.append is atomic; a bare int += would race
        barrier = threading.Barrier(33)  # 32 clients + the killer

        def client(cid):
            for i in range(6):
                try:
                    out = srv.predict(_x(cid * 7 + i), timeout=60)
                    assert len(out) > 0
                    done.append(1)
                except Exception as e:  # noqa: BLE001 — the assertion
                    failures.append(repr(e))
                if i == 1:
                    barrier.wait(timeout=60)  # all 32 in flight post-kill

        def killer():
            barrier.wait(timeout=60)
            monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0")

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(32)] + [threading.Thread(target=killer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures, failures[:5]
        assert len(done) == 32 * 6
        assert failover() >= 1, "no batch ever failed over"
        assert opened() >= 1, "the dead replica's breaker never opened"
        assert mx.telemetry.gauge("serving.replica.healthy").value == 1
        states = {r["id"]: r["state"] for r in srv.stats()["replicas"]}
        assert states[0] == "open" and states[1] == "closed"


def test_all_replicas_down_fast_typed_errors(monkeypatch):
    """Both replicas dead: after the breakers open, requests fail FAST
    with the typed 503-mapped error (NoHealthyReplicas) — well under 2x
    the request deadline, never a hang."""
    no_cap = _delta("serving.no_capacity")
    # cb_errors=1: one failure opens a breaker; probe far in the future
    # so the pool stays provably down for the whole test
    with _server(replicas=2, cb_errors=1, cb_probe_ms=60_000,
                 max_delay_ms=0.0) as srv:
        monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0,1")
        # the opening request: tries both replicas, both fail, error
        # surfaces typed (the injected fault) — and both breakers open
        with pytest.raises(MXNetError):
            srv.predict(_x(), timeout=30)
        assert mx.telemetry.gauge("serving.replica.healthy").value == 0
        deadline_ms = 250.0
        for i in range(5):
            t0 = time.monotonic()
            with pytest.raises(NoHealthyReplicas):
                srv.predict(_x(i), timeout=30, deadline_ms=deadline_ms)
            took = time.monotonic() - t0
            assert took < 2 * deadline_ms / 1e3, (
                f"all-down request took {took * 1e3:.0f} ms — not a fast "
                "typed rejection")
        assert no_cap() >= 5
        assert srv.stats()["status"] == "unavailable"


def test_replica_recovers_after_half_open_probe(monkeypatch):
    """Clear the fault → the opened breaker's half-open probe routes one
    live request through, closes on success, and traffic returns to the
    recovered replica."""
    probes = _delta("serving.replica.probe")
    recovered = _delta("serving.replica.recovered")
    with _server(replicas=2, cb_probe_ms=40.0, max_delay_ms=0.0) as srv:
        monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0")
        for i in range(6):  # opens replica 0's breaker (3 consec errors)
            srv.predict(_x(i), timeout=30)
        assert mx.telemetry.gauge("serving.replica.healthy").value == 1
        monkeypatch.delenv("MXNET_FI_SERVE_RAISE_REPLICA")
        served_before = srv.replicas[0].batches
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            srv.predict(_x(1), timeout=30)
            if (srv.replicas[0].state == "closed"
                    and srv.replicas[0].batches > served_before):
                break
            time.sleep(0.01)
        assert srv.replicas[0].state == "closed", (
            "replica 0 never recovered after the fault cleared")
        assert srv.replicas[0].batches > served_before
        assert probes() >= 1 and recovered() >= 1
        assert mx.telemetry.gauge("serving.replica.healthy").value == 2
        assert srv.stats()["status"] == "ok"


def test_watchdog_times_out_hung_replica(monkeypatch):
    """A hung forward (injected latency >> watchdog) marks the replica
    suspect and the batch fails over — the dispatch path never freezes
    and no client request fails."""
    timeouts = _delta("serving.replica.timeout")
    with _server(replicas=2, replica_timeout_ms=250.0,
                 cb_probe_ms=60_000, max_delay_ms=0.0) as srv:
        monkeypatch.setenv("MXNET_FI_SERVE_LATENCY_MS", "5000")
        monkeypatch.setenv("MXNET_FI_SERVE_LATENCY_REPLICA", "0")
        failures = []

        def client(i):
            try:
                assert len(srv.predict(_x(i), timeout=60)) > 0
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        assert not failures, failures
        assert timeouts() >= 1, "the watchdog never fired"
        assert srv.replicas[0].state == "open"
        assert wall < 5.0, (
            f"requests took {wall:.1f}s — a hung replica froze dispatch")


def test_hedged_request_wins_on_second_replica(monkeypatch):
    """With hedging armed and every forward slowed past the hedge delay,
    a duplicate dispatch fires on the second replica (first result wins;
    the loser is discarded, not surfaced)."""
    hedges = _delta("serving.replica.hedge")
    with _server(replicas=2, hedge_ms=50.0, max_delay_ms=0.0) as srv:
        monkeypatch.setenv("MXNET_FI_SERVE_LATENCY_MS", "300")
        out = srv.predict(_x(), timeout=30)
        assert len(out) > 0
        assert hedges() >= 1, "no hedge was dispatched"
        monkeypatch.delenv("MXNET_FI_SERVE_LATENCY_MS")
        # pool is fully healthy afterwards: hedging is not an error path
        assert mx.telemetry.gauge("serving.replica.healthy").value == 2


def test_fail_every_nth_batch_is_absorbed(monkeypatch):
    """Intermittent faults (every 3rd serving batch attempt raises, any
    replica) are fully absorbed by failover re-dispatch: zero client
    errors."""
    failover = _delta("serving.replica.failover")
    with _server(replicas=2, max_delay_ms=0.0) as srv:
        monkeypatch.setenv("MXNET_FI_SERVE_FAIL_EVERY", "3")
        for i in range(30):
            assert len(srv.predict(_x(i), timeout=30)) > 0
        assert failover() >= 5  # ~10 injected failures, all re-dispatched


def test_reload_failure_ejects_replica_not_pool(monkeypatch):
    """A reload that fails on replica 1 ejects ONLY replica 1: the pool
    keeps serving the NEW weights from replica 0, and a later clean
    reload heals the ejected replica."""
    ejected = _delta("serving.replica.ejected")
    reload_err = _delta("serving.reload_error")
    with _server(replicas=2, max_delay_ms=0.0, seed=3) as srv:
        from mxnet_tpu.predictor import Predictor

        _, params_v2 = _mlp_params(seed=9, scale=2.0)
        v2 = {f"arg:{k}": v for k, v in params_v2.items()}
        monkeypatch.setenv("MXNET_FI_SERVE_RELOAD_CORRUPT", "1")
        assert srv.reload(v2) == 1
        assert ejected() == 1 and reload_err() == 1
        states = {r["id"]: r["state"] for r in srv.stats()["replicas"]}
        assert states[1] == "ejected" and states[0] == "closed"
        assert srv.stats()["status"] == "degraded"
        # traffic still flows, on the NEW weights, bitwise
        x = _x(5)
        ref = Predictor(srv._orig_symbol, v2, {"data": (1, 6)})
        out = srv.predict(x, timeout=30)
        assert out[0].tobytes() == ref.run(data=x[None])[0][0].tobytes()
        # an ejected replica is NOT probe-eligible: time alone must never
        # re-admit weights of unknown consistency
        time.sleep(0.3)
        assert srv.replicas[1].state == "ejected"
        # a clean reload heals it
        monkeypatch.delenv("MXNET_FI_SERVE_RELOAD_CORRUPT")
        assert srv.reload(v2) == 2
        assert srv.replicas[1].state == "closed"
        assert srv.stats()["status"] == "ok"
        assert srv.replicas[1].version == 2


def test_bitwise_determinism_across_replicas(monkeypatch):
    """Per-bucket outputs are bitwise identical regardless of which
    replica served the batch — both driven directly (each replica's
    bucket-1 program) and through failover routing (the future's
    stamped replica id proves who served)."""
    with _server(replicas=2, max_delay_ms=0.0, cb_errors=1,
                 cb_probe_ms=1.0) as srv:
        x = _x(7)
        batch = x[None]
        direct = [srv.predictor(1, replica=r).run(data=batch)[0]
                  for r in (0, 1)]
        assert direct[0].tobytes() == direct[1].tobytes(), (
            "replica programs disagree bitwise for the same bucket")

        # through traffic: kill 0 → served by 1; kill 1 (0 heals via an
        # immediate probe) → served by 0
        monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0")
        f1 = srv.submit({"data": x})
        out1 = f1.result(30)
        monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "1")
        deadline = time.monotonic() + 20
        f2 = srv.submit({"data": x})
        out2 = f2.result(30)
        while f2.replica == f1.replica and time.monotonic() < deadline:
            time.sleep(0.01)
            f2 = srv.submit({"data": x})
            out2 = f2.result(30)
        assert f1.replica != f2.replica, "failover never switched replica"
        assert out1[0].tobytes() == out2[0].tobytes(), (
            f"replica {f1.replica} and {f2.replica} responses differ "
            "bitwise for bucket 1")


def test_no_compile_across_failover_and_hedge(monkeypatch):
    """The warmed request path performs ZERO XLA compiles even while
    batches fail over and hedge across replicas."""
    with _server(replicas=2, hedge_ms=20.0, cb_probe_ms=50.0) as srv:
        compiles = mx.telemetry.counter("executor.jit_compile")
        aot_trace = mx.telemetry.counter("aot.trace_compile")
        c0, a0 = compiles.value, aot_trace.value
        monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0")
        threads = [threading.Thread(
            target=lambda i=i: srv.predict(_x(i), timeout=60))
            for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        monkeypatch.delenv("MXNET_FI_SERVE_RAISE_REPLICA")
        for i in range(8):
            srv.predict(_x(i), timeout=30)
        assert compiles.value - c0 == 0, (
            "XLA compile on the failover/hedge path")
        assert aot_trace.value - a0 == 0


def test_worker_crash_fails_pending_typed_and_restarts():
    """Satellite: an unhandled exception outside the per-batch guard
    (here: a crashing latency observer) must fail pending futures with
    the typed WorkerCrashed, count serving.worker_crash, and restart the
    worker — pounded across several crash/recover cycles."""
    crashes = _delta("serving.worker_crash")
    with _server(replicas=1, buckets=(1, 4), max_delay_ms=5.0) as srv:
        real_observer = srv._batcher._latency_observer

        def bomb(_lat_us):
            raise RuntimeError("observer exploded")

        for cycle in range(4):
            srv._batcher._latency_observer = bomb
            futs = [srv.submit({"data": _x(cycle * 8 + i)})
                    for i in range(6)]
            crashed = 0
            for f in futs:
                try:
                    f.result(30)
                except WorkerCrashed:
                    crashed += 1
            assert crashed >= 1, "no future saw the typed crash error"
            # recover: the restarted worker must serve fresh traffic
            srv._batcher._latency_observer = real_observer
            assert len(srv.predict(_x(cycle), timeout=30)) > 0
        assert crashes() >= 4
        assert srv._batcher.running


def test_admission_scales_with_healthy_capacity():
    """Graceful degradation: the effective admission bound is
    queue_depth x healthy fraction — a half-dead pool sheds at half
    depth with Retry-After semantics instead of deadline-expiring a full
    queue; zero capacity fails typed."""
    frac = [1.0]
    entered = threading.Event()
    release = threading.Event()

    def runner(bucket, stacked, n_valid):
        entered.set()
        assert release.wait(30)
        return [np.zeros((bucket, 1), np.float32)]

    b = DynamicBatcher(runner, buckets=(1,), max_delay=0.0, queue_depth=8,
                       capacity_fn=lambda: frac[0])
    b.start()
    try:
        x = {"data": np.zeros((2,), np.float32)}
        b.submit(dict(x))  # taken by the worker, blocks in runner
        assert entered.wait(10)
        for _ in range(4):
            b.submit(dict(x))  # 4 queued: half of queue_depth
        frac[0] = 0.5  # half the pool died: effective depth is now 4
        with pytest.raises(ServerOverloaded):
            b.submit(dict(x))
        frac[0] = 1.0  # recovered: full depth admits again
        b.submit(dict(x))
        frac[0] = 0.0  # everything died: typed fast rejection
        with pytest.raises(NoHealthyReplicas):
            b.submit(dict(x))
    finally:
        release.set()
        b.stop(drain=True)


def test_healthz_readiness_degraded_and_unavailable(monkeypatch):
    """Satellite: /healthz is readiness-aware — 200 + degraded:true with
    per-replica states while partially healthy, 503 (with body) when no
    replica is healthy, so an external LB can eject the process."""
    import json
    import urllib.error
    import urllib.request

    from mxnet_tpu.serving import make_http_server

    with _server(replicas=2, cb_errors=1, cb_probe_ms=60_000,
                 max_delay_ms=0.0) as srv:
        httpd = make_http_server(srv, host="127.0.0.1", port=0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            def healthz():
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/healthz",
                            timeout=30) as r:
                        return r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            code, body = healthz()
            assert code == 200 and body["status"] == "ok"
            assert body["degraded"] is False
            assert len(body["replicas"]) == 2

            monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0")
            srv.predict(_x(), timeout=30)  # opens replica 0 (cb_errors=1)
            code, body = healthz()
            assert code == 200 and body["status"] == "degraded"
            assert body["degraded"] is True
            assert body["healthy_replicas"] == 1
            states = {r["id"]: r["state"] for r in body["replicas"]}
            assert states[0] == "open"

            monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0,1")
            with pytest.raises(Exception):
                srv.predict(_x(), timeout=30)  # opens replica 1 too
            code, body = healthz()
            assert code == 503, "zero healthy replicas must be 503"
            assert body["status"] == "unavailable"
            assert body["healthy_replicas"] == 0
        finally:
            httpd.shutdown()
            httpd.server_close()


def test_http_body_size_cap():
    """Satellite: a POST whose Content-Length exceeds
    MXNET_SERVING_MAX_BODY_BYTES is refused with 413 before the body is
    read; fresh connections still serve."""
    import json
    import urllib.error
    import urllib.request

    from mxnet_tpu.serving import make_http_server

    with _server(replicas=1, max_body_bytes=2048) as srv:
        httpd = make_http_server(srv, host="127.0.0.1", port=0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            big = json.dumps(
                {"inputs": {"data": [0.0] * 4000}}).encode()
            assert len(big) > 2048
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=big,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 413
            assert mx.telemetry.counter(
                "serving.http.body_too_large").value >= 1

            x = _x()
            body = json.dumps({"inputs": {"data": x.tolist()}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                payload = json.loads(r.read())
            assert len(payload["outputs"]) > 0
        finally:
            httpd.shutdown()
            httpd.server_close()


def test_replica_auto_resolution_on_cpu():
    """replicas=0 (auto) degenerates to ONE replica on CPU — today's
    single-device behavior — even with virtual devices present; an
    explicit ask beyond the device count clamps."""
    with _server(replicas=0, started=False) as srv:
        assert len(srv.replicas) == 1
    with _server(replicas=64, started=False) as srv:
        assert len(srv.replicas) == 8  # conftest forces 8 virtual devices
