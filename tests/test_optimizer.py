"""Optimizer tests (reference test_optimizer.py): each optimizer against a
numpy reference implementation."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

rs = np.random.RandomState(9)


def _run_updates(opt, w0, g_seq):
    w = mx.nd.array(w0.copy())
    state = opt.create_state(0, w)
    for g in g_seq:
        opt.update(0, w, mx.nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w0 = rs.randn(10).astype(np.float32)
    gs = [rs.randn(10).astype(np.float32) for _ in range(5)]
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.01, rescale_grad=1.0)
    got = _run_updates(opt, w0, gs)
    w = w0.copy()
    for g in gs:
        w = w - 0.1 * (g + 0.01 * w)
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


def test_sgd_momentum_matches_numpy():
    w0 = rs.randn(10).astype(np.float32)
    gs = [rs.randn(10).astype(np.float32) for _ in range(5)]
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, rescale_grad=1.0)
    got = _run_updates(opt, w0, gs)
    w, mom = w0.copy(), np.zeros_like(w0)
    for g in gs:
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    assert_almost_equal(got, w, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    w0 = rs.randn(10).astype(np.float32)
    gs = [rs.randn(10).astype(np.float32) for _ in range(5)]
    opt = mx.optimizer.Adam(learning_rate=0.01, rescale_grad=1.0)
    got = _run_updates(opt, w0, gs)
    w = w0.astype(np.float64).copy()
    m, v = np.zeros_like(w), np.zeros_like(w)
    b1, b2, eps = 0.9, 0.999, 1e-8
    for t, g in enumerate(gs, 1):
        lr = 0.01 * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g ** 2
        w = w - lr * m / (np.sqrt(v) + eps)
    assert_almost_equal(got, w.astype(np.float32), rtol=1e-4, atol=1e-5)


def test_rmsprop_matches_numpy():
    w0 = rs.randn(10).astype(np.float32)
    gs = [rs.randn(10).astype(np.float32) for _ in range(5)]
    opt = mx.optimizer.RMSProp(learning_rate=0.01, gamma1=0.9, rescale_grad=1.0)
    got = _run_updates(opt, w0, gs)
    w = w0.astype(np.float64).copy()
    n = np.zeros_like(w)
    for g in gs:
        n = 0.1 * g ** 2 + 0.9 * n
        w = w - 0.01 * g / np.sqrt(n + 1e-8)
    assert_almost_equal(got, w.astype(np.float32), rtol=1e-4, atol=1e-5)


def test_clip_gradient():
    w0 = np.zeros(3, dtype=np.float32)
    g = np.array([10.0, -10.0, 0.5], dtype=np.float32)
    opt = mx.optimizer.SGD(learning_rate=1.0, clip_gradient=1.0, rescale_grad=1.0)
    got = _run_updates(opt, w0, [g])
    assert_almost_equal(got, -np.clip(g, -1, 1), rtol=1e-6)


def test_lr_scheduler_factor():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched, rescale_grad=1.0)
    w = mx.nd.zeros((1,))
    for i in range(25):
        opt.update(0, w, mx.nd.ones((1,)), None)
    # after 25 updates two decays have fired (derived from num_update;
    # base_lr itself stays the initial lr)
    assert sched(opt.num_update) == 0.25


def test_lr_wd_mult():
    opt = mx.optimizer.SGD(
        learning_rate=0.1, param_idx2name={0: "fc_weight", 1: "fc_bias"},
        wd=0.1, rescale_grad=1.0,
    )
    opt.set_lr_mult({"fc_weight": 0.0})
    w = mx.nd.ones((2,))
    before = w.asnumpy().copy()
    opt.update(0, w, mx.nd.ones((2,)), opt.create_state(0, w))
    assert_almost_equal(w.asnumpy(), before)  # lr 0 → no change
    # bias gets wd_mult=0 automatically (name doesn't end in _weight/_gamma)
    b = mx.nd.ones((2,))
    opt.update(1, b, mx.nd.zeros((2,)), opt.create_state(1, b))
    assert_almost_equal(b.asnumpy(), np.ones(2))  # zero grad + no wd → no change


def test_updater_states_serialization():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, rescale_grad=1.0)
    updater = mx.optimizer.get_updater(opt)
    w = mx.nd.ones((4,))
    updater(0, mx.nd.ones((4,)), w)
    blob = updater.get_states()
    updater2 = mx.optimizer.get_updater(
        mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, rescale_grad=1.0)
    )
    updater2.set_states(blob)
    assert 0 in updater2.states
    assert_almost_equal(
        updater2.states[0].asnumpy(), updater.states[0].asnumpy()
    )


def test_create_by_name():
    for name in ["sgd", "adam", "rmsprop", "adagrad", "adadelta", "nag",
                 "sgld", "ftrl", "dcasgd", "test"]:
        opt = mx.optimizer.create(name)
        assert isinstance(opt, mx.optimizer.Optimizer)
    with pytest.raises(ValueError):
        mx.optimizer.create("nonexistent_optimizer")
