"""C++ binding (cpp_package/mxtpu_cpp.hpp): the reference's cpp-package
analogue. Builds the bundled lenet_inference example against the
amalgamated library and checks its output against the Python framework
(the reference's cpp-package ci_test.sh pattern)."""

import os
import subprocess
import sysconfig

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.test_utils import assert_almost_equal

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def amalgamated(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("amal"))
    r = subprocess.run(
        ["python", os.path.join(_ROOT, "tools", "amalgamation.py"),
         "--out-dir", out_dir],
        capture_output=True, text=True, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr
    return out_dir


def test_cpp_lenet_example(amalgamated, tmp_path):
    sym = models.lenet(num_classes=10)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 1, 28, 28))],
             label_shapes=[("softmax_label", (2,))])
    mx.random.seed(11)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "lenet")
    mod.save_checkpoint(prefix, 0)

    exe = str(tmp_path / "lenet_inference")
    libdir = sysconfig.get_config_var("LIBDIR")
    r = subprocess.run(
        ["g++", "-std=c++17", "-O2",
         os.path.join(_ROOT, "cpp_package", "example", "lenet_inference.cc"),
         "-o", exe, f"-I{amalgamated}",
         f"-I{os.path.join(_ROOT, 'cpp_package')}",
         os.path.join(amalgamated, "libmxtpu.so"),
         f"-Wl,-rpath,{amalgamated}", f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [exe, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr + r.stdout
    got = np.array([float(x) for x in r.stdout.split()], np.float32)

    x = (np.arange(2 * 28 * 28, dtype=np.float32) % 29 / 29.0).reshape(
        2, 1, 28, 28)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], []), is_train=False)
    expect = mod.get_outputs()[0].asnumpy().ravel()
    assert got.shape == expect.shape
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)
    # imperative surface: argmax printed on stderr
    assert "argmax:" in r.stderr
    want = expect.reshape(2, 10).argmax(1)
    assert f"argmax: {want[0]} {want[1]}" in r.stderr
