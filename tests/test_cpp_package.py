"""C++ binding (cpp_package/mxtpu_cpp.hpp): the reference's cpp-package
analogue. Builds the bundled lenet_inference example against the
amalgamated library and checks its output against the Python framework
(the reference's cpp-package ci_test.sh pattern)."""

import os
import subprocess
import sysconfig

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.test_utils import assert_almost_equal

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def amalgamated(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("amal"))
    r = subprocess.run(
        ["python", os.path.join(_ROOT, "tools", "amalgamation.py"),
         "--out-dir", out_dir],
        capture_output=True, text=True, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr
    return out_dir


def test_cpp_lenet_example(amalgamated, tmp_path):
    sym = models.lenet(num_classes=10)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 1, 28, 28))],
             label_shapes=[("softmax_label", (2,))])
    mx.random.seed(11)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "lenet")
    mod.save_checkpoint(prefix, 0)

    exe = str(tmp_path / "lenet_inference")
    libdir = sysconfig.get_config_var("LIBDIR")
    r = subprocess.run(
        ["g++", "-std=c++17", "-O2",
         os.path.join(_ROOT, "cpp_package", "example", "lenet_inference.cc"),
         "-o", exe, f"-I{amalgamated}",
         f"-I{os.path.join(_ROOT, 'cpp_package')}",
         os.path.join(amalgamated, "libmxtpu.so"),
         f"-Wl,-rpath,{amalgamated}", f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [exe, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr + r.stdout
    got = np.array([float(x) for x in r.stdout.split()], np.float32)

    x = (np.arange(2 * 28 * 28, dtype=np.float32) % 29 / 29.0).reshape(
        2, 1, 28, 28)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], []), is_train=False)
    expect = mod.get_outputs()[0].asnumpy().ravel()
    assert got.shape == expect.shape
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)
    # imperative surface: argmax printed on stderr
    assert "argmax:" in r.stderr
    want = expect.reshape(2, 10).argmax(1)
    assert f"argmax: {want[0]} {want[1]}" in r.stderr


def test_cpp_lenet_built_from_ops_trains(amalgamated, tmp_path):
    """The construction tier end to end: the C++ example builds LeNet from
    generated op wrappers (no JSON load), SimpleBind allocates, and one
    SGD step runs through MXKVStoreSetUpdater + MXImperativeInvoke. Its
    before/after losses must match the identical flow in Python."""
    r = subprocess.run(
        ["python", os.path.join(_ROOT, "tools", "gen_cpp_wrappers.py")],
        capture_output=True, text=True, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr
    exe = str(tmp_path / "lenet_train")
    libdir = sysconfig.get_config_var("LIBDIR")
    r = subprocess.run(
        ["g++", "-std=c++17", "-O2",
         os.path.join(_ROOT, "cpp_package", "example", "lenet_train.cc"),
         "-o", exe, f"-I{amalgamated}",
         f"-I{os.path.join(_ROOT, 'cpp_package')}",
         os.path.join(amalgamated, "libmxtpu.so"),
         f"-Wl,-rpath,{amalgamated}", f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([exe], capture_output=True, text=True, env=env,
                       timeout=300)
    assert r.returncode == 0, r.stderr + r.stdout
    loss0_c, loss1_c = (float(x) for x in r.stdout.split())

    # ---- identical flow in python ----
    B, CLS = 8, 10
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = mx.sym.Activation(c1, act_type="tanh", name="act1")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        name="pool1")
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = mx.sym.Activation(c2, act_type="tanh", name="act2")
    p2 = mx.sym.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="max",
                        name="pool2")
    fl = mx.sym.Flatten(p2, name="flat")
    f1 = mx.sym.FullyConnected(fl, num_hidden=500, name="fc1")
    a3 = mx.sym.Activation(f1, act_type="tanh", name="act3")
    f2 = mx.sym.FullyConnected(a3, num_hidden=CLS, name="fc2")
    net = mx.sym.SoftmaxOutput(f2, name="softmax")

    exe_py = net.simple_bind(mx.cpu(), data=(B, 1, 28, 28),
                             softmax_label=(B,))
    for n in net.list_arguments():
        a = exe_py.arg_dict[n]
        size = int(np.prod(a.shape))
        if n == "data":
            v = (np.arange(size) % 29) / 29.0
        elif n == "softmax_label":
            v = np.arange(size) % CLS
        else:
            v = 0.05 * np.sin(np.arange(size) % 1997)
        a[:] = v.reshape(a.shape).astype(np.float32)

    def loss_py():
        p = exe_py.outputs[0].asnumpy()
        lbl = (np.arange(B) % CLS).astype(int)
        return float(np.mean(-np.log(p[np.arange(B), lbl] + 1e-12)))

    exe_py.forward(is_train=True)
    loss0_py = loss_py()
    exe_py.backward()
    for n in net.list_arguments():
        if n in ("data", "softmax_label"):
            continue
        g = exe_py.grad_dict[n]
        exe_py.arg_dict[n][:] = exe_py.arg_dict[n].asnumpy() \
            - 0.01 * g.asnumpy()
    exe_py.forward(is_train=True)
    loss1_py = loss_py()

    assert abs(loss0_c - loss0_py) < 1e-4, (loss0_c, loss0_py)
    assert abs(loss1_c - loss1_py) < 1e-3, (loss1_c, loss1_py)
    assert loss1_c < loss0_c  # the C-driven update actually learned
