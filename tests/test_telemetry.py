"""Telemetry subsystem (ISSUE 2): registry semantics, span recording,
hot-path instrumentation wiring, and the host+device trace merge."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import telemetry as tm  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_registry():
    tm.reset()
    spans = tm.spans_enabled()
    yield
    tm.enable_spans(spans)
    tm.reset()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------
def test_counter_gauge_histogram_basics():
    c = tm.counter("t.c")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert tm.counter("t.c") is c  # same handle on re-lookup

    g = tm.gauge("t.g")
    g.set(3)
    g.set(1)
    assert g.value == 1 and g.max == 3

    h = tm.histogram("t.h")
    for v in (10, 2, 8):
        h.observe(v)
    assert h.count == 3 and h.sum == 20 and h.min == 2 and h.max == 10


def test_kind_collision_raises():
    tm.counter("t.kind")
    with pytest.raises(TypeError):
        tm.gauge("t.kind")


def test_reset_keeps_handles_valid():
    c = tm.counter("t.reset")
    c.inc(7)
    tm.reset()
    assert c.value == 0
    c.inc()
    assert tm.counter("t.reset").value == 1


def test_snapshot_nests_on_dots():
    tm.counter("a.b.c").inc(2)
    tm.gauge("a.b.g").set(9)
    snap = tm.snapshot()
    assert snap["a"]["b"]["c"] == 2
    assert snap["a"]["b"]["g"]["value"] == 9


def test_snapshot_instrument_nested_under_instrument():
    # "n.h" (a histogram whose rendering is itself a dict) and "n.h.retries"
    # must come out as two distinct metrics, not merge into one dict
    tm.histogram("n.h").observe(3)
    tm.counter("n.h.retries").inc(2)
    snap = tm.snapshot()
    assert snap["n"]["h"][""]["count"] == 1
    assert snap["n"]["h"]["retries"] == 2


def test_enable_spans_mid_span_records_cleanly():
    tm.enable_spans(False)
    s = tm.span("mid.span")
    s.__enter__()
    tm.enable_spans(True)  # e.g. from a callback while fit spans are open
    s.__exit__(None, None, None)
    assert [e["name"] for e in tm.events()] == ["mid.span"]


def test_dump_writes_json_and_prometheus(tmp_path):
    tm.counter("d.count").inc(3)
    tm.histogram("d.hist").observe(5)
    json_path, prom_path = tm.dump(str(tmp_path / "snap.json"))
    with open(json_path) as f:
        snap = json.load(f)
    assert snap["d"]["count"] == 3
    prom = open(prom_path).read()
    assert "# TYPE mxnet_d_count counter" in prom
    assert "mxnet_d_count 3" in prom
    assert "mxnet_d_hist_count 1" in prom
    assert "mxnet_d_hist_sum 5" in prom


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
def test_span_histogram_always_on_events_gated():
    tm.enable_spans(False)
    with tm.span("t.phase"):
        pass
    assert tm.histogram("t.phase").count == 1
    assert tm.events() == []

    tm.enable_spans(True)
    with tm.span("t.phase", detail="x"):
        pass
    evts = tm.events()
    assert len(evts) == 1
    ev = evts[0]
    assert ev["name"] == "t.phase" and ev["ph"] == "X"
    assert ev["dur"] >= 1 and "ts" in ev and "pid" in ev and "tid" in ev
    assert ev["args"] == {"detail": "x"}
    assert tm.histogram("t.phase").count == 2


def test_dump_trace_and_merge(tmp_path):
    tm.enable_spans(True)
    with tm.span("fit.data_wait"):
        pass
    host_path = tm.dump_trace(str(tmp_path / "host.json"))
    device_path = str(tmp_path / "device.json")
    with open(device_path, "w") as f:
        json.dump({"traceEvents": [
            {"name": "fusion", "ph": "X", "ts": 1, "dur": 2,
             "pid": 99, "tid": 1}],
            "metadata": {"clock": "tsc"}}, f)
    out = tm.merge_chrome_trace(host_path, device_path,
                                str(tmp_path / "merged.json"))
    with open(out) as f:
        merged = json.load(f)
    names = {e["name"] for e in merged["traceEvents"]}
    assert {"fit.data_wait", "fusion"} <= names
    assert merged["metadata"] == {"clock": "tsc"}  # device metadata kept


def test_merge_accepts_event_list_and_missing_device(tmp_path):
    tm.enable_spans(True)
    with tm.span("host.only"):
        pass
    out = tm.merge_chrome_trace(tm.events(), None,
                                str(tmp_path / "host_only.json"))
    with open(out) as f:
        merged = json.load(f)
    assert [e["name"] for e in merged["traceEvents"]] == ["host.only"]


def test_trace_merge_cli_smoke(tmp_path):
    """tools/trace_merge.py merges a host span file + gzipped device trace."""
    import gzip

    host = tmp_path / "host.json"
    with open(host, "w") as f:
        json.dump({"traceEvents": [
            {"name": "fit.dispatch", "ph": "X", "ts": 5, "dur": 3,
             "pid": 1, "tid": 1}]}, f)
    device = tmp_path / "device.trace.json.gz"
    with gzip.open(device, "wt") as f:
        json.dump({"traceEvents": [
            {"name": "xla_op", "ph": "X", "ts": 6, "dur": 1,
             "pid": 2, "tid": 2}]}, f)
    out = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "trace_merge.py"),
         str(host), str(device), "-o", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    with open(out) as f:
        merged = json.load(f)
    assert {e["name"] for e in merged["traceEvents"]} == {
        "fit.dispatch", "xla_op"}


# ---------------------------------------------------------------------------
# hot-path wiring
# ---------------------------------------------------------------------------
def test_prefetch_iter_counters():
    rng = np.random.RandomState(0)
    it = mx.io.NDArrayIter(
        rng.uniform(size=(32, 4)).astype(np.float32),
        rng.randint(0, 3, (32,)).astype(np.float32),
        batch_size=8, last_batch_handle="discard")
    pf = mx.io.DevicePrefetchIter(it)
    n = sum(1 for _ in pf)
    pf.close()
    assert n == 4
    assert tm.counter("io.prefetch.batches").value == 4
    assert tm.histogram("io.prefetch.consumer_wait_us").count == 5  # +EOF


def test_metric_counters_device_vs_fallback():
    rng = np.random.RandomState(1)
    p = rng.uniform(0.05, 1.0, (16, 4)).astype(np.float32)
    labels = [mx.nd.array(rng.randint(0, 4, (16,)).astype(np.float32))]
    preds = [mx.nd.array(p / p.sum(axis=1, keepdims=True))]

    m = mx.metric.Accuracy()
    m.device_update(labels, preds)
    assert tm.counter("metric.device_update").value == 1
    assert tm.counter("metric.numpy_fallback").value == 0
    m.get()
    assert tm.counter("metric.drain_sync").value == 1

    class NoDevice(mx.metric.Accuracy):
        def _device_batch(self, label, pred):
            return None

    NoDevice().device_update(labels, preds)
    assert tm.counter("metric.numpy_fallback").value == 1


def test_kvstore_counters():
    kv = mx.kv.create("local")
    a = mx.nd.array(np.ones((4, 4), np.float32))
    kv.init("w", a)
    kv.push("w", mx.nd.array(np.full((4, 4), 2.0, np.float32)))
    out = mx.nd.array(np.zeros((4, 4), np.float32))
    kv.pull("w", out=out)
    assert tm.counter("kvstore.push").value == 1
    assert tm.counter("kvstore.push_bytes").value == 64
    assert tm.counter("kvstore.pull").value == 1
    assert tm.counter("kvstore.pull_bytes").value == 64


def test_executor_jit_cache_counters():
    d = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3), grad_req="null")
    tm.reset()
    # forward is lazy: reading an output materializes (and jit-builds) it
    exe.forward(is_train=False, data=mx.nd.array(np.ones((2, 3), np.float32)))
    _ = exe.outputs[0].shape
    compiles = tm.counter("executor.jit_compile").value
    assert compiles >= 1
    exe.forward(is_train=False, data=mx.nd.array(np.ones((2, 3), np.float32)))
    _ = exe.outputs[0].shape
    assert tm.counter("executor.jit_compile").value == compiles  # no recompile
    assert tm.counter("executor.jit_cache_hit").value >= 1


def test_sync_counters_count_blocking_reads():
    a = mx.nd.array(np.ones((2, 2), np.float32))
    base = tm.counter("ndarray.asnumpy").value
    a.asnumpy()
    assert tm.counter("ndarray.asnumpy").value == base + 1
    a.wait_to_read()
    assert tm.counter("ndarray.wait_to_read").value == 1


def test_speedometer_phase_breakdown(caplog):
    import logging as _logging

    from mxnet_tpu.callback import Speedometer

    with tm.span("fit.dispatch"):
        sum(range(1000))

    class Param:
        epoch, nbatch = 0, 1
        eval_metric = None

    s = Speedometer(batch_size=8, frequent=1, phases=True)
    p = Param()
    with caplog.at_level(_logging.INFO):
        s(p)  # arms meter + phase window
        with tm.span("fit.dispatch"):
            sum(range(1000))
        p.nbatch = 2
        s(p)
    assert any("Phases:" in r.message and "dispatch=" in r.message
               for r in caplog.records)


def test_bucketing_switch_counters():
    """switch_bucket mirrors the executor.jit_compile invariant:
    bucketing.switch counts active-bucket changes, and
    bucketing.compile_on_switch counts only switches that had to BIND a
    new bucket — steady-state bucket misses must read as zero."""
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=10, output_dim=6, name="emb")
        pooled = mx.sym.sum(emb, axis=1)
        net = mx.sym.FullyConnected(pooled, num_hidden=4, name="fc")
        return mx.sym.SoftmaxOutput(net, name="softmax"), ("data",), \
            ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    tm.reset()
    for key, dshape in [(8, (4, 8)), (4, (4, 4)), (8, (4, 8)), (4, (4, 4))]:
        batch = mx.io.DataBatch(
            data=[mx.nd.ones(dshape)], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", dshape)],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))],
        )
        mod.forward(batch, is_train=False)
    # 8->4, 4->8, 8->4: three active-bucket changes, ONE new bucket bound
    assert tm.counter("bucketing.switch").value == 3
    assert tm.counter("bucketing.compile_on_switch").value == 1
    # steady state: revisiting bound buckets binds nothing new
    compile_before = tm.counter("bucketing.compile_on_switch").value
    for key, dshape in [(8, (4, 8)), (4, (4, 4))]:
        batch = mx.io.DataBatch(
            data=[mx.nd.ones(dshape)], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", dshape)],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))],
        )
        mod.forward(batch, is_train=False)
    assert tm.counter("bucketing.compile_on_switch").value == compile_before


# ---------------------------------------------------------------------------
# per-kernel device-time attribution (ISSUE 18)
# ---------------------------------------------------------------------------


def _xevt(name, dur, hlo_op=None, extra_args=None, ph="X"):
    args = {"hlo_op": hlo_op or name}
    if extra_args:
        args.update(extra_args)
    return {"ph": ph, "name": name, "dur": dur, "ts": 0, "pid": 1, "tid": 1,
            "args": args}


def test_kernel_table_aggregates_and_ranks():
    evts = [
        _xevt("convolution.1", 100.0),
        _xevt("convolution.1", 50.0),   # second call aggregates
        _xevt("fusion.7", 200.0,
              extra_args={"bytes_accessed": "1,024"}),
        _xevt("reduce.2", 25.0),
        # non-kernel rows must be skipped: host span (no hlo_op),
        # metadata (ph=M), counter event
        {"ph": "X", "name": "fit.dispatch", "dur": 999.0, "args": {}},
        {"ph": "M", "name": "process_name", "args": {"hlo_op": "x"}},
        {"ph": "C", "name": "mem", "args": {"hlo_op": "x"}, "dur": 5.0},
    ]
    table = tm.kernel_table(evts)
    assert [r["name"] for r in table] == ["fusion.7", "convolution.1",
                                         "reduce.2"]
    conv = table[1]
    assert conv["device_us"] == 150.0 and conv["calls"] == 2
    assert table[0]["bytes"] == 1024
    # pct is the share of ATTRIBUTED device time (host spans excluded)
    assert table[0]["pct"] == pytest.approx(200.0 / 375.0, abs=1e-4)
    assert sum(r["pct"] for r in table) == pytest.approx(1.0, abs=1e-3)


def test_kernel_table_top_n_and_trace_dict(tmp_path):
    evts = [_xevt(f"op.{i}", float(i + 1)) for i in range(15)]
    table = tm.kernel_table({"traceEvents": evts}, top=10)
    assert len(table) == 10
    assert table[0]["name"] == "op.14"  # heaviest first
    path = tmp_path / "trace.json"
    path.write_text(json.dumps({"traceEvents": evts}))
    assert tm.kernel_table(str(path), top=3) == table[:3]


def test_kernel_table_empty_trace():
    assert tm.kernel_table([]) == []
    assert tm.kernel_table({"traceEvents": []}) == []
