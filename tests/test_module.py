"""Module tests incl. training convergence (reference test_module.py +
trainer smoke tests tests/python/train/test_mlp.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

rs = np.random.RandomState(5)


def _toy_data(n=512, d=16, k=3, seed=42):
    r = np.random.RandomState(seed)
    W = r.randn(d, k)
    X = r.randn(n, d).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.float32)
    return X, Y


def _mlp(k=3):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=24, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=k, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_basic_api():
    net = _mlp()
    mod = mx.mod.Module(net)
    assert mod.data_names == ["data"]
    assert mod.label_names == ["softmax_label"]
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    assert mod.binded
    mod.init_params()
    assert mod.params_initialized
    arg_params, aux_params = mod.get_params()
    assert set(arg_params) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    assert mod.output_shapes[0][1] == (8, 3)


def test_module_fit_converges():
    X, Y = _toy_data()
    train = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp())
    mod.fit(
        train, optimizer="sgd", optimizer_params={"learning_rate": 0.5},
        num_epoch=10, initializer=mx.init.Xavier(),
    )
    acc = mod.score(val, "acc")[0][1]
    assert acc > 0.9, f"accuracy {acc}"


def test_module_fit_adam():
    X, Y = _toy_data()
    train = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp())
    mod.fit(
        train, optimizer="adam", optimizer_params={"learning_rate": 0.05},
        num_epoch=10, initializer=mx.init.Xavier(),
    )
    acc = mod.score(val, "acc")[0][1]
    assert acc > 0.9, f"accuracy {acc}"


def test_module_update_on_kvstore_paths():
    """Both update paths (local updater vs kvstore updater) must agree."""
    X, Y = _toy_data(n=128)
    results = {}
    for kv in [None, "local"]:
        mx.random.seed(0)
        train = mx.io.NDArrayIter(X, Y, batch_size=32)
        mod = mx.mod.Module(_mlp())
        mod.fit(
            train, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, num_epoch=3,
            initializer=mx.init.Uniform(0.05),
        )
        arg_params, _ = mod.get_params()
        results[str(kv)] = {k: v.asnumpy() for k, v in arg_params.items()}
    for k in results["None"]:
        assert_almost_equal(
            results["None"][k], results["local"][k], rtol=1e-4, atol=1e-5,
            names=(f"no-kv:{k}", f"local-kv:{k}"),
        )


def test_module_checkpoint_roundtrip():
    X, Y = _toy_data(n=128)
    train = mx.io.NDArrayIter(X, Y, batch_size=32)
    val = mx.io.NDArrayIter(X, Y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.fit(
        train, optimizer="sgd", optimizer_params={"learning_rate": 0.2},
        num_epoch=3, initializer=mx.init.Xavier(),
    )
    score = mod.score(val, "acc")[0][1]
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "m")
        mod.save_checkpoint(prefix, 3, save_optimizer_states=True)
        assert os.path.exists(f"{prefix}-symbol.json")
        assert os.path.exists(f"{prefix}-0003.params")
        assert os.path.exists(f"{prefix}-0003.states")
        mod2 = mx.mod.Module.load(prefix, 3)
        mod2.bind(val.provide_data, val.provide_label, for_training=False)
        assert mod2.score(val, "acc")[0][1] == score


def test_module_predict():
    X, Y = _toy_data(n=128)
    val = mx.io.NDArrayIter(X, Y, batch_size=32)
    mod = mx.mod.Module(_mlp())
    mod.bind(val.provide_data, val.provide_label, for_training=False)
    mod.init_params()
    out = mod.predict(val)
    assert out.shape == (128, 3)
    assert_almost_equal(out.asnumpy().sum(axis=1), np.ones(128), rtol=1e-4)


def test_module_forward_reshape():
    mod = mx.mod.Module(_mlp())
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    batch = mx.io.DataBatch(
        data=[mx.nd.ones((4, 16))], label=[mx.nd.zeros((4,))],
        provide_data=[mx.io.DataDesc("data", (4, 16))],
        provide_label=[mx.io.DataDesc("softmax_label", (4,))],
    )
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (4, 3)


def test_module_fixed_params():
    net = _mlp()
    mod = mx.mod.Module(net, fixed_param_names=["fc1_weight", "fc1_bias"])
    mod.bind(data_shapes=[("data", (8, 16))], label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 1.0})
    w_before = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy().copy()
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(8, 16).astype(np.float32))],
        label=[mx.nd.zeros((8,))],
    )
    mod.forward_backward(batch)
    mod.update()
    w_after = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy()
    assert np.array_equal(w_before, w_after)  # frozen
    # but fc2 moved
    assert not np.array_equal(
        w_before.sum(), mod._exec_group._exec.arg_dict["fc2_weight"].asnumpy().sum()
    )


def test_sequential_module():
    net1 = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8, name="fc1")
    net2 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3, name="fc2"),
        name="softmax",
    )
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net1, label_names=None))
    seq.add(
        mx.mod.Module(net2), take_labels=True, auto_wiring=True
    )
    X, Y = _toy_data(n=64)
    train = mx.io.NDArrayIter(X, Y, batch_size=32)
    seq.bind(train.provide_data, train.provide_label)
    seq.init_params()
    seq.init_optimizer(optimizer_params={"learning_rate": 0.1})
    batch = next(iter(train))
    seq.forward(batch)
    assert seq.get_outputs()[0].shape == (32, 3)
    seq.backward()
    seq.update()


def test_bucketing_module():
    """LSTM-free bucketing check: per-bucket graphs share params."""
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(
            data, input_dim=10, output_dim=6, name="shared_emb"
        )
        pooled = mx.sym.sum(emb, axis=1)  # (batch, 6), invariant to seq_len
        net = mx.sym.FullyConnected(pooled, num_hidden=4, name="shared_fc")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(
        data_shapes=[("data", (4, 8))], label_shapes=[("softmax_label", (4,))]
    )
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    for key, dshape in [(8, (4, 8)), (4, (4, 4)), (8, (4, 8))]:
        batch = mx.io.DataBatch(
            data=[mx.nd.ones(dshape)], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", dshape)],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))],
        )
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # same weight object across buckets → shapes differ in data, weight shared
    w8 = mod._buckets[8]._exec_group._exec.arg_dict.get("shared_fc_weight")
    w4 = mod._buckets[4]._exec_group._exec.arg_dict.get("shared_fc_weight")
    assert w8 is not None and w4 is not None
    assert np.array_equal(w8.asnumpy(), w4.asnumpy())


def test_forward_with_new_batch_shape_keeps_trained_params():
    """Regression: Module.forward on a batch of a NEW shape triggers an
    executor-group reshape; the rebound executor must share the live
    trained parameters — it used to reallocate them as zeros, silently
    resetting training on any mid-epoch partial batch."""
    rs = np.random.RandomState(0)
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=6, name="rw_fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 5))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Uniform(0.5))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    b = mx.io.DataBatch(data=[mx.nd.array(rs.randn(8, 5).astype(np.float32))],
                        label=[mx.nd.array(np.zeros(8, np.float32))])
    for _ in range(3):
        mod.forward_backward(b)
        mod.update()
    w_before = mod._exec_group._exec.arg_dict["rw_fc_weight"].asnumpy()
    assert np.abs(w_before).max() > 0

    # partial batch (different shape) flows through reshape
    b_small = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(3, 5).astype(np.float32))],
        label=[mx.nd.array(np.zeros(3, np.float32))])
    mod.forward(b_small, is_train=False)
    assert mod.get_outputs()[0].shape == (3, 6)
    w_after = mod._exec_group._exec.arg_dict["rw_fc_weight"].asnumpy()
    np.testing.assert_array_equal(w_before, w_after)

    # and training continues from the same weights after reshaping back
    mod.forward_backward(b)
    mod.update()
    w_cont = mod._exec_group._exec.arg_dict["rw_fc_weight"].asnumpy()
    assert not np.allclose(w_cont, w_after)  # an update happened
