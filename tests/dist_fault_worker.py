"""Worker for the fault-injection distributed training test.

Reference analogue: ps-lite's scheduler notices a dead node
(``src/kvstore/kvstore_dist.h:177-185``) and restarted servers rejoin via
``is_recovery``. Here recovery is the launcher's whole-job restart
(tools/launch.py --max-restarts) PLUS checkpoint auto-resume: on the FIRST
attempt rank 1 hard-crashes mid-epoch (faultinject os._exit — no cleanup,
like a real kill; MXNET_FI_CRASH_AT_BATCH/MXNET_FI_RANK set by the test),
the supervisor tears the job down and relaunches all ranks, and the second
attempt must RESUME from the checkpointed epoch (not epoch 0) — rank 0
writes barrier-fenced checkpoints to the shared MXNET_CHECKPOINT_DIR —
then train to convergence with ``kv.num_dead_node`` reporting the
recovery.
"""

import logging
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    logging.basicConfig(level=logging.INFO, stream=sys.stdout)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    attempt = int(os.environ.get("MXNET_NUM_RESTARTS", "0"))

    rng = np.random.RandomState(42)
    X = rng.randn(128, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)
    Xs, Ys = X[rank::nw], Y[rank::nw]  # 64 samples/rank, 4 batches/epoch

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(Xs, Ys, batch_size=16)

    ckpt_dir = os.environ["MXNET_CHECKPOINT_DIR"]
    loaded = mx.checkpoint.load_latest(ckpt_dir)
    resume_epoch = loaded.next_epoch if loaded is not None else 0
    print(f"rank {rank} attempt {attempt} RESUME epoch={resume_epoch}",
          flush=True)
    if attempt > 0:
        # the whole point: the relaunch continues mid-training, not from 0
        assert loaded is not None and resume_epoch > 0, (
            f"rank {rank}: post-restart attempt found no checkpoint to "
            "resume from")

    mx.random.seed(7)
    mod.fit(
        it, num_epoch=25, kvstore=kv, initializer=mx.init.Xavier(),
        optimizer="sgd",
        optimizer_params={"learning_rate": 0.2, "rescale_grad": 1.0 / nw},
    )
    metric = mx.metric.Accuracy()
    acc = mod.score(it, metric)[0][1]
    assert acc > 0.8, f"rank {rank}: post-recovery training stuck at {acc}"
    assert kv.num_dead_node == attempt, (
        f"rank {rank}: num_dead_node={kv.num_dead_node}, expected "
        f"{attempt} recovered death(s)"
    )
    kv.barrier()
    print(f"rank {rank}/{nw} FAULT-RECOVERY OK acc={acc:.3f} "
          f"dead={kv.num_dead_node} resumed_from={resume_epoch}",
          flush=True)


if __name__ == "__main__":
    main()
