"""Worker for the fault-injection distributed training test.

Reference analogue: ps-lite's scheduler notices a dead node
(``src/kvstore/kvstore_dist.h:177-185``) and restarted servers rejoin via
``is_recovery``. Here recovery is the launcher's whole-job restart
(tools/launch.py --max-restarts): on the FIRST attempt rank 1 hard-crashes
mid-epoch (os._exit — no cleanup, like a real kill), the supervisor tears
the job down and relaunches all ranks, and the second attempt must train to
convergence with ``kv.num_dead_node`` reporting the recovery.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    attempt = int(os.environ.get("MXNET_NUM_RESTARTS", "0"))

    rng = np.random.RandomState(42)
    X = rng.randn(128, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)
    Xs, Ys = X[rank::nw], Y[rank::nw]

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(Xs, Ys, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(7)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(
        kvstore=kv, optimizer="sgd",
        optimizer_params={"learning_rate": 0.2, "rescale_grad": 1.0 / nw},
    )
    metric = mx.metric.Accuracy()
    step = 0
    for epoch in range(25):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
            step += 1
            if attempt == 0 and rank == 1 and epoch == 3:
                # simulate a mid-training machine death: no cleanup, no
                # barrier — surviving ranks are left inside the job
                print(f"rank {rank} CRASHING at epoch {epoch}", flush=True)
                os._exit(17)
    acc = metric.get()[1]
    assert acc > 0.8, f"rank {rank}: post-recovery training stuck at {acc}"
    assert kv.num_dead_node == 1, (
        f"rank {rank}: num_dead_node={kv.num_dead_node}, expected the one "
        "recovered death"
    )
    kv.barrier()
    print(f"rank {rank}/{nw} FAULT-RECOVERY OK acc={acc:.3f} "
          f"dead={kv.num_dead_node}", flush=True)


if __name__ == "__main__":
    main()
