"""accnn low-rank compression (tools/accnn.py; reference tools/accnn)."""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

from accnn import factorize  # noqa: E402


def _lenet_like():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=16, name="conv1")
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    c2 = mx.sym.Convolution(p1, kernel=(3, 3), num_filter=32, pad=(1, 1),
                            name="conv2")
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f1 = mx.sym.FullyConnected(mx.sym.Flatten(p2), num_hidden=64, name="fc1")
    a3 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a3, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def _task(rs, n=256):
    y = rs.randint(0, 4, n).astype(np.float32)
    x = (rs.rand(n, 1, 20, 20) * 0.2
         + y[:, None, None, None] / 4.0).astype(np.float32)
    return x, y


def _accuracy(mod, x, y):
    metric = mx.metric.Accuracy()
    for i in range(0, len(y), 32):
        b = mx.io.DataBatch(data=[mx.nd.array(x[i:i + 32])],
                            label=[mx.nd.array(y[i:i + 32])])
        mod.forward(b, is_train=False)
        mod.update_metric(metric, b.label)
    return metric.get()[1]


def _fit(mod, x, y, epochs, lr=0.01):
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": lr},
                       force_init=True)
    for _ in range(epochs):
        for i in range(0, len(y), 32):
            b = mx.io.DataBatch(data=[mx.nd.array(x[i:i + 32])],
                                label=[mx.nd.array(y[i:i + 32])])
            mod.forward_backward(b)
            mod.update()


def test_accnn_compresses_and_finetunes():
    mx.random.seed(0)
    np.random.seed(0)
    rs = np.random.RandomState(1)
    x, y = _task(rs)
    sym = _lenet_like()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (32, 1, 20, 20))],
             label_shapes=[("softmax_label", (32,))])
    mod.init_params(initializer=mx.init.Xavier())
    _fit(mod, x, y, epochs=8)
    base_acc = _accuracy(mod, x, y)
    assert base_acc > 0.9, base_acc

    args, auxs = mod.get_params()
    new_sym, new_args, report = factorize(
        sym, args, speedup=1.5, data_shape=(1, 20, 20), min_rank=2)
    # the conv/fc layers actually split
    names = set(new_sym.list_arguments())
    assert "conv2_v_weight" in names and "conv2_h_weight" in names
    assert "fc1_v_weight" in names and "fc1_h_weight" in names
    assert "conv2_weight" not in names
    assert report["conv2"][0] < report["conv2"][1]  # genuinely low-rank

    mod2 = mx.mod.Module(new_sym, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (32, 1, 20, 20))],
              label_shapes=[("softmax_label", (32,))])
    mod2.init_params(arg_params=new_args, aux_params=auxs,
                     allow_missing=False)
    # SVD init alone keeps the model usable...
    svd_acc = _accuracy(mod2, x, y)
    # ...and the reference recipe (brief fine-tune at a REDUCED lr — the
    # training lr overshoots on the factored net and can walk a perfect
    # model down to ~0.8) recovers accuracy
    _fit(mod2, x, y, epochs=3, lr=0.001)
    tuned_acc = _accuracy(mod2, x, y)
    assert tuned_acc > max(0.9, base_acc - 0.05), (base_acc, svd_acc,
                                                   tuned_acc)


def test_accnn_full_rank_keeps_layer():
    rs = np.random.RandomState(0)
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="tiny")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Flatten(c), num_hidden=2, name="out"),
        name="softmax")
    args = {
        "tiny_weight": mx.nd.array(rs.randn(4, 1, 3, 3).astype(np.float32)),
        "tiny_bias": mx.nd.zeros((4,)),
        "out_weight": mx.nd.array(rs.randn(2, 144).astype(np.float32)),
        "out_bias": mx.nd.zeros((2,)),
    }
    # a generous budget drives ranks to full, where splitting would only
    # add FLOPs: the layer is kept verbatim
    new_sym, new_args, report = factorize(
        sym, args, speedup=0.5, data_shape=(1, 8, 8), min_rank=1)
    assert "tiny_weight" in new_sym.list_arguments()
    assert report["tiny"][0] == report["tiny"][1]

    # skip= excludes a layer from factorization entirely
    new_sym2, _, report2 = factorize(
        sym, args, speedup=4.0, data_shape=(1, 8, 8), min_rank=1,
        skip=("tiny",))
    assert "tiny_weight" in new_sym2.list_arguments()
    assert "tiny" not in report2


def test_accnn_skips_dilated_heads_and_clamps_tiny_layers():
    rs = np.random.RandomState(0)
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(2, 2),
                           dilate=(2, 2), name="dil")
    # "mid" is a tiny interior FC (2 singular values < min_rank=4: the
    # clamp must keep it at full rank, not crash); "out" feeds only the
    # loss head and must be excluded as the classifier
    mid = mx.sym.Activation(
        mx.sym.FullyConnected(mx.sym.Flatten(c), num_hidden=2, name="mid"),
        act_type="relu")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mid, num_hidden=3, name="out"),
        name="softmax")
    args = {
        "dil_weight": mx.nd.array(rs.randn(4, 1, 3, 3).astype(np.float32)),
        "dil_bias": mx.nd.zeros((4,)),
        "mid_weight": mx.nd.array(rs.randn(2, 1024).astype(np.float32)),
        "mid_bias": mx.nd.zeros((2,)),
        "out_weight": mx.nd.array(rs.randn(3, 2).astype(np.float32)),
        "out_bias": mx.nd.zeros((3,)),
    }
    new_sym, new_args, report = factorize(
        sym, args, speedup=4.0, data_shape=(1, 16, 16), min_rank=4)
    arg_names = new_sym.list_arguments()
    assert "dil_weight" in arg_names and "dil" not in report
    assert "out_weight" in arg_names and "out" not in report  # head kept
    # the tiny FC hit the clamp: full rank, layer kept verbatim
    assert "mid_weight" in arg_names
    assert report["mid"][0] == report["mid"][1] == 2
    # graph still binds with the returned params
    exe = new_sym.simple_bind(mx.cpu(), grad_req="null", data=(1, 1, 16, 16))
    exe.copy_params_from(new_args, {})
