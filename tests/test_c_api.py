"""Core C ABI end-to-end: a pure-C program runs LeNet inference.

Reference parity: the ~150-function C ABI (include/mxnet/c_api.h,
src/c_api/c_api.cc) is the foundation all language bindings sit on
(SURVEY.md §1 layers 9-11). This test exercises the TPU-native core subset
exactly the way a binding would: build the amalgamated single .so + header
(tools/amalgamation.py — the reference's amalgamation/ analogue), compile a
plain-C client against them, and have it load a symbol JSON + .params
checkpoint, bind an executor, run forward and print the output — which must
match the Python framework bit-for-bit (same XLA program underneath).
"""

import os
import subprocess
import sysconfig

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.test_utils import assert_almost_equal

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_C_CLIENT = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "mxtpu.h"

#define CHK(x) if ((x) != 0) { \
  fprintf(stderr, "FAIL %s: %s\n", #x, MXGetLastError()); return 1; }

/* strict C99: strdup is POSIX-only (an implicit declaration would truncate
 * the returned pointer on LP64 and crash) */
static char* dupstr(const char* s) {
  size_t n = strlen(s) + 1;
  char* p = malloc(n);
  memcpy(p, s, n);
  return p;
}

int main(int argc, char** argv) {
  const char* sym_file = argv[1];
  const char* param_file = argv[2];

  SymbolHandle sym;
  CHK(MXSymbolCreateFromFile(sym_file, &sym));

  uint32_t n_args, n_aux;
  const char **arg_names, **aux_names;
  CHK(MXSymbolListArguments(sym, &n_args, &arg_names));
  /* copy: the scratch is reused by later calls on this handle */
  char** args_copy = malloc(n_args * sizeof(char*));
  for (uint32_t i = 0; i < n_args; ++i) args_copy[i] = dupstr(arg_names[i]);
  CHK(MXSymbolListAuxiliaryStates(sym, &n_aux, &aux_names));
  char** aux_copy = malloc(n_aux * sizeof(char*));
  for (uint32_t i = 0; i < n_aux; ++i) aux_copy[i] = dupstr(aux_names[i]);

  /* load the checkpoint (arg:/aux: prefixed keys, reference format) */
  uint32_t n_loaded, n_names;
  NDArrayHandle* loaded;
  const char** loaded_names;
  CHK(MXNDArrayLoad(param_file, &n_loaded, &loaded, &n_names, &loaded_names));
  NDArrayHandle* loaded_copy = malloc(n_loaded * sizeof(NDArrayHandle));
  char** lnames = malloc(n_loaded * sizeof(char*));
  for (uint32_t i = 0; i < n_loaded; ++i) {
    loaded_copy[i] = loaded[i];
    lnames[i] = dupstr(loaded_names[i]);
  }

  /* infer shapes from the data shape to size data/label arrays */
  const char* keys[] = {"data"};
  uint32_t indptr[] = {0, 4};
  uint32_t dims[] = {2, 1, 28, 28};
  uint32_t in_size, out_size_s, aux_size;
  const uint32_t *in_ndim, *out_ndim_s, *aux_ndim;
  const uint32_t **in_dims, **out_dims_s, **aux_dims;
  int complete;
  CHK(MXSymbolInferShape(sym, 1, keys, indptr, dims, &in_size, &in_ndim,
                         &in_dims, &out_size_s, &out_ndim_s, &out_dims_s,
                         &aux_size, &aux_ndim, &aux_dims, &complete));
  if (!complete) { fprintf(stderr, "infer incomplete\n"); return 1; }

  /* build in_args: params from checkpoint, data/label created here */
  NDArrayHandle* in_args = malloc(n_args * sizeof(NDArrayHandle));
  uint32_t* req = malloc(n_args * sizeof(uint32_t));
  for (uint32_t i = 0; i < n_args; ++i) {
    req[i] = 0; /* null: inference */
    in_args[i] = NULL;
    char key[256];
    snprintf(key, sizeof key, "arg:%s", args_copy[i]);
    for (uint32_t j = 0; j < n_loaded; ++j)
      if (strcmp(lnames[j], key) == 0) in_args[i] = loaded_copy[j];
    if (!in_args[i]) { /* data or label: create from inferred shape */
      CHK(MXNDArrayCreate(in_dims[i], in_ndim[i], 1, 0, 0, &in_args[i]));
    }
  }
  NDArrayHandle* aux = malloc((n_aux ? n_aux : 1) * sizeof(NDArrayHandle));
  for (uint32_t i = 0; i < n_aux; ++i) {
    aux[i] = NULL;
    char key[256];
    snprintf(key, sizeof key, "aux:%s", aux_copy[i]);
    for (uint32_t j = 0; j < n_loaded; ++j)
      if (strcmp(lnames[j], key) == 0) aux[i] = loaded_copy[j];
    if (!aux[i]) {
      CHK(MXNDArrayCreate(aux_dims[i], aux_ndim[i], 1, 0, 0, &aux[i]));
    }
  }

  /* feed a deterministic input */
  float* input = malloc(2 * 28 * 28 * sizeof(float));
  for (int i = 0; i < 2 * 28 * 28; ++i) input[i] = (float)(i % 29) / 29.0f;
  for (uint32_t i = 0; i < n_args; ++i) {
    if (strcmp(args_copy[i], "data") == 0)
      CHK(MXNDArraySyncCopyFromCPU(in_args[i], input, 2 * 28 * 28));
  }

  ExecutorHandle exe;
  CHK(MXExecutorBind(sym, 1, 0, n_args, in_args, NULL, req, n_aux, aux,
                     &exe));
  CHK(MXExecutorForward(exe, 0));

  uint32_t n_out;
  NDArrayHandle* outs;
  CHK(MXExecutorOutputs(exe, &n_out, &outs));
  uint32_t od;
  const uint32_t* oshape;
  CHK(MXNDArrayGetShape(outs[0], &od, &oshape));
  uint32_t total = 1;
  for (uint32_t i = 0; i < od; ++i) total *= oshape[i];
  float* out = malloc(total * sizeof(float));
  CHK(MXNDArraySyncCopyToCPU(outs[0], out, total));
  for (uint32_t i = 0; i < total; ++i) printf("%.6f\n", out[i]);

  /* sanity on the registry surface too */
  uint32_t n_ops; const char** op_names;
  CHK(MXListAllOpNames(&n_ops, &op_names));
  if (n_ops < 100) { fprintf(stderr, "op registry too small\n"); return 1; }

  CHK(MXExecutorFree(exe));
  CHK(MXSymbolFree(sym));
  return 0;
}
"""


@pytest.fixture(scope="module")
def amalgamated(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("amal"))
    r = subprocess.run(
        ["python", os.path.join(_ROOT, "tools", "amalgamation.py"),
         "--out-dir", out_dir],
        capture_output=True, text=True, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr
    return out_dir


def test_pure_c_lenet_inference(amalgamated, tmp_path):
    # LeNet checkpoint written by the Python framework
    sym = models.lenet(num_classes=10)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 1, 28, 28))],
             label_shapes=[("softmax_label", (2,))])
    mx.random.seed(7)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "lenet")
    mod.save_checkpoint(prefix, 0)

    # compile the pure-C client against the single header + .so
    csrc = str(tmp_path / "client.c")
    with open(csrc, "w") as f:
        f.write(_C_CLIENT)
    client = str(tmp_path / "client")
    libdir = sysconfig.get_config_var("LIBDIR")
    r = subprocess.run(
        ["gcc", "-std=c99", "-O2", csrc, "-o", client,
         f"-I{amalgamated}", os.path.join(amalgamated, "libmxtpu.so"),
         f"-Wl,-rpath,{amalgamated}", f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [client, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr + r.stdout
    got = np.array([float(x) for x in r.stdout.split()], np.float32)

    # oracle: the same forward through the Python API
    x = (np.arange(2 * 28 * 28, dtype=np.float32) % 29 / 29.0).reshape(
        2, 1, 28, 28)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], []), is_train=False)
    expect = mod.get_outputs()[0].asnumpy().ravel()
    assert got.shape == expect.shape
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)


def test_c_api_ndarray_roundtrip_and_save(amalgamated, tmp_path):
    """NDArray C surface via ctypes: create/copy/shape/dtype/save/load."""
    import ctypes

    lib = ctypes.CDLL(os.path.join(amalgamated, "libmxtpu.so"))
    lib.MXGetLastError.restype = ctypes.c_char_p

    h = ctypes.c_void_p()
    shape = (ctypes.c_uint32 * 2)(3, 4)
    assert lib.MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, ctypes.byref(h)) == 0, \
        lib.MXGetLastError()
    data = np.arange(12, dtype=np.float32)
    assert lib.MXNDArraySyncCopyFromCPU(
        h, data.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(12)) == 0
    out = np.zeros(12, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(12)) == 0
    np.testing.assert_array_equal(out, data)

    ndim = ctypes.c_uint32()
    pdata = ctypes.POINTER(ctypes.c_uint32)()
    assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    assert ndim.value == 2 and [pdata[i] for i in range(2)] == [3, 4]
    dt = ctypes.c_int()
    assert lib.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0
    assert dt.value == 0  # float32

    # save with key, load back through the Python side to prove the file
    # is the reference-binary .params container
    fname = str(tmp_path / "x.params").encode()
    keys = (ctypes.c_char_p * 1)(b"weight")
    arr = (ctypes.c_void_p * 1)(h)
    assert lib.MXNDArraySave(fname, 1, arr, keys) == 0, lib.MXGetLastError()
    loaded = mx.nd.load(fname.decode())
    np.testing.assert_array_equal(loaded["weight"].asnumpy(),
                                  data.reshape(3, 4))
    assert lib.MXNDArrayFree(h) == 0


def test_c_api_imperative_invoke_and_views(amalgamated, tmp_path):
    """The imperative tier: creators enumerate the registry, and
    MXImperativeInvoke runs ops eagerly on NDArray handles (the
    reference's generated-nd.* foundation, c_api_ndarray.cc:396).
    Views (Reshape/Slice/At) and symbol attr get/set round-trip."""
    import ctypes

    lib = ctypes.CDLL(os.path.join(amalgamated, "libmxtpu.so"))
    lib.MXGetLastError.restype = ctypes.c_char_p

    # creators <-> names
    n = ctypes.c_uint32()
    creators = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXSymbolListAtomicSymbolCreators(
        ctypes.byref(n), ctypes.byref(creators)) == 0
    assert n.value >= 200
    name = ctypes.c_char_p()
    by_name = {}
    for i in range(n.value):
        c = ctypes.c_void_p(creators[i])
        assert lib.MXSymbolGetAtomicSymbolName(c, ctypes.byref(name)) == 0
        by_name[name.value.decode()] = ctypes.c_void_p(creators[i])
    assert "Activation" in by_name and "dot" in by_name

    # x = arange(6)-2 as (2,3); y = relu(x) via imperative invoke
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint32 * 2)(2, 3)
    assert lib.MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, ctypes.byref(h)) == 0
    data = (np.arange(6, dtype=np.float32) - 2).reshape(2, 3)
    assert lib.MXNDArraySyncCopyFromCPU(
        h, data.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(6)) == 0

    n_out = ctypes.c_int()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    keys = (ctypes.c_char_p * 1)(b"act_type")
    vals = (ctypes.c_char_p * 1)(b"relu")
    ins = (ctypes.c_void_p * 1)(h)
    assert lib.MXImperativeInvoke(
        by_name["Activation"], 1, ins, ctypes.byref(n_out),
        ctypes.byref(outs), 1, keys, vals) == 0, lib.MXGetLastError()
    assert n_out.value == 1
    y = ctypes.c_void_p(outs[0])
    buf = np.zeros(6, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        y, buf.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(6)) == 0
    np.testing.assert_array_equal(buf.reshape(2, 3), np.maximum(data, 0))

    # caller-provided outputs (the reference's non-null *outputs out= path)
    o = ctypes.c_void_p()
    assert lib.MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, ctypes.byref(o)) == 0
    outs2 = (ctypes.c_void_p * 1)(o)
    outs2_p = ctypes.cast(outs2, ctypes.POINTER(ctypes.c_void_p))
    n_out2 = ctypes.c_int(1)
    assert lib.MXImperativeInvoke(
        by_name["Activation"], 1, ins, ctypes.byref(n_out2),
        ctypes.byref(outs2_p), 1, keys, vals) == 0, lib.MXGetLastError()
    buf2 = np.zeros(6, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        o, buf2.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(6)) == 0
    np.testing.assert_array_equal(buf2.reshape(2, 3), np.maximum(data, 0))
    lib.MXNDArrayFree(o)

    # views: reshape to (3,2), slice rows, index
    r = ctypes.c_void_p()
    dims = (ctypes.c_int * 2)(3, 2)
    assert lib.MXNDArrayReshape(h, 2, dims, ctypes.byref(r)) == 0
    nd_dim = ctypes.c_uint32()
    pdata = ctypes.POINTER(ctypes.c_uint32)()
    assert lib.MXNDArrayGetShape(r, ctypes.byref(nd_dim),
                                 ctypes.byref(pdata)) == 0
    assert [pdata[i] for i in range(nd_dim.value)] == [3, 2]
    s = ctypes.c_void_p()
    assert lib.MXNDArraySlice(r, 1, 3, ctypes.byref(s)) == 0
    assert lib.MXNDArrayGetShape(s, ctypes.byref(nd_dim),
                                 ctypes.byref(pdata)) == 0
    assert [pdata[i] for i in range(nd_dim.value)] == [2, 2]
    a = ctypes.c_void_p()
    assert lib.MXNDArrayAt(s, 0, ctypes.byref(a)) == 0

    # write-through views (reference aliasing contract): fill a batch
    # row by row through sliced handles, then read the PARENT
    batch_h = ctypes.c_void_p()
    bshape = (ctypes.c_uint32 * 2)(3, 4)
    assert lib.MXNDArrayCreateEx(bshape, 2, 1, 0, 0, 0,
                                 ctypes.byref(batch_h)) == 0
    for i in range(3):
        row = ctypes.c_void_p()
        assert lib.MXNDArraySlice(batch_h, i, i + 1, ctypes.byref(row)) == 0
        rowdata = np.full((1, 4), float(i + 1), np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(
            row, rowdata.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_size_t(4)) == 0
        lib.MXNDArrayFree(row)
    whole = np.zeros((3, 4), np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        batch_h, whole.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(12)) == 0
    np.testing.assert_array_equal(
        whole, np.repeat([[1.0], [2.0], [3.0]], 4, axis=1))
    lib.MXNDArrayFree(batch_h)

    # symbol attrs
    sym = ctypes.c_void_p()
    js = mx.sym.Variable("w").tojson().encode()
    assert lib.MXSymbolCreateFromJSON(js, ctypes.byref(sym)) == 0
    assert lib.MXSymbolSetAttr(sym, b"__mood__", b"great") == 0
    out_s = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert lib.MXSymbolGetAttr(sym, b"__mood__", ctypes.byref(out_s),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and out_s.value == b"great"
    for handle in (h, y, r, s, a):
        lib.MXNDArrayFree(handle)
    lib.MXSymbolFree(sym)


def test_c_api_kvstore_recordio_dataiter(amalgamated, tmp_path):
    """Tier-3 C surface: KVStore init/push/pull through handles, RecordIO
    write/read roundtrip, and a CSVIter driven batch-by-batch — the
    remaining MX* families every binding consumes (reference c_api.h
    MXKVStore*/MXRecordIO*/MXDataIter*)."""
    import ctypes

    lib = ctypes.CDLL(os.path.join(amalgamated, "libmxtpu.so"))
    lib.MXGetLastError.restype = ctypes.c_char_p

    # --- KVStore: init key 3 to ones, push 2x, pull back 3x (local sums)
    kv = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    t = ctypes.c_char_p()
    assert lib.MXKVStoreGetType(kv, ctypes.byref(t)) == 0
    assert t.value == b"local"
    r = ctypes.c_int()
    assert lib.MXKVStoreGetRank(kv, ctypes.byref(r)) == 0 and r.value == 0

    def make_nd(vals):
        h = ctypes.c_void_p()
        shape = (ctypes.c_uint32 * 1)(len(vals))
        assert lib.MXNDArrayCreateEx(shape, 1, 1, 0, 0, 0,
                                     ctypes.byref(h)) == 0
        arr = np.asarray(vals, np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(
            h, arr.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_size_t(len(vals))) == 0
        return h

    keys = (ctypes.c_int * 1)(3)
    init_v = (ctypes.c_void_p * 1)(make_nd([1.0, 1.0]))
    assert lib.MXKVStoreInit(kv, 1, keys, init_v) == 0, lib.MXGetLastError()
    push_v = (ctypes.c_void_p * 1)(make_nd([2.0, 5.0]))
    assert lib.MXKVStorePush(kv, 1, keys, push_v, 0) == 0
    out_h = make_nd([0.0, 0.0])
    pull_v = (ctypes.c_void_p * 1)(out_h)
    assert lib.MXKVStorePull(kv, 1, keys, pull_v, 0) == 0
    got = np.zeros(2, np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        out_h, got.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(2)) == 0
    np.testing.assert_array_equal(got, [2.0, 5.0])

    # --- string-key (Ex) trio on the same store: names address entries
    # independently of the int keyspace (roadmap-5b ledger slice)
    skeys = (ctypes.c_char_p * 2)(b"fc1_weight", b"fc1_bias")
    sinit = (ctypes.c_void_p * 2)(make_nd([1.0, 2.0]), make_nd([3.0, 4.0]))
    assert lib.MXKVStoreInitEx(kv, 2, skeys, sinit) == 0, \
        lib.MXGetLastError()
    spush = (ctypes.c_void_p * 2)(make_nd([10.0, 20.0]),
                                  make_nd([30.0, 40.0]))
    assert lib.MXKVStorePushEx(kv, 2, skeys, spush, 0) == 0, \
        lib.MXGetLastError()
    souts = [make_nd([0.0, 0.0]), make_nd([0.0, 0.0])]
    spull = (ctypes.c_void_p * 2)(*souts)
    assert lib.MXKVStorePullEx(kv, 2, skeys, spull, 0) == 0, \
        lib.MXGetLastError()
    for h_out, want in zip(souts, ([10.0, 20.0], [30.0, 40.0])):
        sgot = np.zeros(2, np.float32)
        assert lib.MXNDArraySyncCopyToCPU(
            h_out, sgot.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_size_t(2)) == 0
        np.testing.assert_array_equal(sgot, want)
    assert lib.MXKVStoreFree(kv) == 0

    # --- RecordIO roundtrip through the C surface
    rec_path = str(tmp_path / "c.rec").encode()
    w = ctypes.c_void_p()
    assert lib.MXRecordIOWriterCreate(rec_path, ctypes.byref(w)) == 0
    payloads = [b"hello", b"tpu" * 40, b""]
    for p in payloads:
        assert lib.MXRecordIOWriterWriteRecord(
            w, p, ctypes.c_size_t(len(p))) == 0
    assert lib.MXRecordIOWriterFree(w) == 0
    rd = ctypes.c_void_p()
    assert lib.MXRecordIOReaderCreate(rec_path, ctypes.byref(rd)) == 0
    buf = ctypes.c_char_p()
    size = ctypes.c_size_t()
    out_payloads = []
    while True:
        assert lib.MXRecordIOReaderReadRecord(
            rd, ctypes.byref(buf), ctypes.byref(size)) == 0
        if size.value == 0 and buf.value is None:
            break
        out_payloads.append(ctypes.string_at(buf, size.value))
    assert lib.MXRecordIOReaderFree(rd) == 0
    assert out_payloads[:2] == payloads[:2]

    # --- DataIter: CSVIter over a small file, batch by batch
    n_it = ctypes.c_uint32()
    its = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXListDataIters(ctypes.byref(n_it), ctypes.byref(its)) == 0
    name = ctypes.c_char_p()
    csv_creator = None
    for i in range(n_it.value):
        c = ctypes.c_void_p(its[i])
        assert lib.MXDataIterGetIterInfo(
            c, ctypes.byref(name), None, None, None, None, None) == 0
        if name.value == b"CSVIter":
            csv_creator = ctypes.c_void_p(its[i])
    assert csv_creator is not None
    csv = tmp_path / "d.csv"
    data = np.arange(24, dtype=np.float32).reshape(8, 3)
    np.savetxt(csv, data, delimiter=",", fmt="%.1f")
    ikeys = (ctypes.c_char_p * 3)(b"data_csv", b"data_shape", b"batch_size")
    ivals = (ctypes.c_char_p * 3)(str(csv).encode(), b"(3,)", b"4")
    it = ctypes.c_void_p()
    assert lib.MXDataIterCreateIter(csv_creator, 3, ikeys, ivals,
                                    ctypes.byref(it)) == 0, \
        lib.MXGetLastError()
    rows = []
    has = ctypes.c_int()
    while True:
        assert lib.MXDataIterNext(it, ctypes.byref(has)) == 0
        if not has.value:
            break
        dh = ctypes.c_void_p()
        assert lib.MXDataIterGetData(it, ctypes.byref(dh)) == 0
        batch = np.zeros((4, 3), np.float32)
        assert lib.MXNDArraySyncCopyToCPU(
            dh, batch.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_size_t(12)) == 0
        rows.append(batch.copy())
        lib.MXNDArrayFree(dh)
    assert lib.MXDataIterBeforeFirst(it) == 0
    assert lib.MXDataIterNext(it, ctypes.byref(has)) == 0 and has.value == 1
    assert lib.MXDataIterFree(it) == 0
    np.testing.assert_array_equal(np.concatenate(rows), data)


def test_capi_construction_and_autograd_surface():
    """Python half of the construction + autograd tiers (the C functions
    are thin marshalling over these; the C end-to-end path is covered by
    cpp_package's lenet_train example test)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import capi

    # atomic + compose, keyword-wired
    s = capi.sym_create_atomic("FullyConnected", ["num_hidden"], ["4"])
    d = capi.sym_create_variable("data")
    capi.sym_compose(s, "fc", ["data"], [d])
    assert s.list_arguments() == ["data", "fc_weight", "fc_bias"]
    # composing twice refuses
    import pytest as _pytest
    from mxnet_tpu.base import MXNetError

    with _pytest.raises(MXNetError, match="already composed"):
        capi.sym_compose(s, "fc2", [], [d])

    # simple_bind allocates; null grad_req leaves gradient slots empty
    exe, in_args, arg_grads, aux = capi.exec_simple_bind(
        s, 1, 0, [], [], [], ["data", "fc_weight", "fc_bias"],
        ["null", "write", "write"], ["data"], [(2, 3)], [], [])
    assert [a.shape for a in in_args] == [(2, 3), (4, 3), (4,)]
    assert arg_grads[0] is None and arg_grads[1] is not None

    # autograd tier
    x = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    g = mx.nd.zeros((2, 2))
    capi.autograd_mark_variables([x], [g], [1])
    prev = capi.autograd_set_recording(1)
    y = (x * x).sum()
    capi.autograd_set_recording(prev)
    capi.autograd_backward([y], [], 0)
    got = capi.nd_get_grad(x).asnumpy()
    np.testing.assert_allclose(got, 2 * x.asnumpy(), rtol=1e-5)
