"""Process-level distributed kvstore test.

Spawns real local processes through tools/launch.py --launcher local (the
reference's nightly tracker pattern) running tests/dist_worker.py, which
asserts exact reduction arithmetic across ranks — the port of
``tests/nightly/dist_sync_kvstore.py:22-58``.
"""

import os
import socket
import subprocess
import sys

import numpy as np

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.dist_multiprocess
def test_dist_training_converges_identically():
    """dist_lenet analogue: 2 ranks train on disjoint shards through the
    dist kvstore; both converge and end with identical parameters."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", "2", "--launcher", "local", "--port", str(_free_port()),
        sys.executable, os.path.join(_ROOT, "tests", "dist_train_worker.py"),
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"dist training failed:\n{out[-4000:]}"
    for r in range(2):
        assert f"rank {r}/2 DIST-TRAIN OK" in out, out[-4000:]


def test_launcher_detects_and_restarts_dead_worker(tmp_path):
    """Failure detection: a dead rank triggers a WHOLE-JOB restart (a
    single-rank relaunch cannot rejoin a stalled jax.distributed job; the
    ps-lite scheduler-liveness + is_recovery analogue is job recovery)."""
    marker = str(tmp_path / "died_once")
    script = str(tmp_path / "flaky.py")
    with open(script, "w") as f:
        f.write(
            "import os, sys\n"
            f"marker = {marker!r}\n"
            "rank = os.environ['MXNET_PROC_ID']\n"
            "if rank == '1' and not os.path.exists(marker):\n"
            "    open(marker, 'w').close()\n"
            "    sys.exit(3)  # simulated crash on first life\n"
            "nr = os.environ['MXNET_NUM_RESTARTS']\n"
            "print(f'rank {rank} alive restarts={nr}', flush=True)\n"
        )
    env = dict(os.environ)
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", "2", "--launcher", "local", "--port", str(_free_port()),
        "--max-restarts", "1",
        sys.executable, script,
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "rank 1 died" in out and "whole-job restart 1/1" in out, out
    # every rank of the second life sees the surfaced restart count
    assert "rank 1 alive restarts=1" in out, out
    assert "rank 0 alive restarts=1" in out, out

    # with no restart budget the job fails and reports the dead rank
    os.unlink(marker)
    cmd[cmd.index("--max-restarts") + 1] = "0"
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120)
    out = proc.stdout + proc.stderr
    assert proc.returncode != 0
    assert "restart budget spent" in out


@pytest.mark.dist_multiprocess
@pytest.mark.parametrize("nproc", [2, 3])
def test_dist_sync_kvstore_local_processes(nproc):
    env = dict(os.environ)
    # workers must initialise their own jax runtime on CPU, not inherit the
    # test process's virtual-device settings
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", str(nproc), "--launcher", "local",
        "--port", str(_free_port()),
        sys.executable, os.path.join(_ROOT, "tests", "dist_worker.py"),
    ]
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=300
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"dist job failed:\n{out[-4000:]}"
    for r in range(nproc):
        assert f"rank {r}/{nproc} DIST OK" in out, out[-4000:]


@pytest.mark.dist_multiprocess
def test_mid_training_worker_kill_recovers_and_converges(tmp_path):
    """Fault injection at FULL depth: rank 1 hard-dies (faultinject
    os._exit, no cleanup) in the middle of epoch 3 of a real dist_sync
    training run — the survivors are mid-collective — and the launcher's
    whole-job restart must bring the job back, RESUMED from the
    checkpointed epoch (rank 0 writes barrier-fenced checkpoints to the
    shared dir; not from epoch 0), to convergence, with kv.num_dead_node
    reporting the recovered death on every rank (reference: ps-lite
    dead-node detection + is_recovery, src/kvstore/kvstore_dist.h:177-195)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_CHECKPOINT_DIR"] = str(tmp_path / "ckpts")
    # rank 1 dies at global batch 14 = epoch 3, batch 2 (4 batches/epoch),
    # first attempt only
    env["MXNET_FI_CRASH_AT_BATCH"] = "14"
    env["MXNET_FI_RANK"] = "1"
    env["MXNET_FI_ATTEMPT"] = "0"
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", "2", "--launcher", "local", "--port", str(_free_port()),
        "--max-restarts", "2",
        sys.executable, os.path.join(_ROOT, "tests", "dist_fault_worker.py"),
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"fault recovery failed:\n{out[-4000:]}"
    assert "faultinject: CRASH at train batch 14" in out, out[-4000:]
    assert "whole-job restart 1/2" in out, out[-4000:]
    # the post-restart attempt resumed from the checkpointed epoch, not 0
    assert "attempt 1 RESUME epoch=3" in out, out[-4000:]
    assert "Resuming from checkpoint" in out, out[-4000:]
    for r in range(2):
        assert f"rank {r}/2 FAULT-RECOVERY OK" in out, out[-4000:]
    assert "dead=1" in out and "resumed_from=3" in out, out[-4000:]


def test_async_wire_format_roundtrip():
    """The dist_async wire protocol is typed frames (header + dtype/shape
    + raw bytes), not pickle — nothing on the wire can execute code."""
    from mxnet_tpu import kvstore_async as ka

    a, b = socket.socketpair()
    try:
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        key = b"k" * 32
        a.sendall(ka._pack_frame(ka._OP_PUSH, "w0", arr, flags=1,
                                 secret=key))
        op, flags, k, got = ka._recv_frame(b, secret=key)
        assert op == ka._OP_PUSH and k == "w0" and flags & 1
        np.testing.assert_array_equal(got, arr)
        assert "import pickle" not in open(ka.__file__).read()
    finally:
        a.close()
        b.close()


def test_async_server_rejects_garbage_and_bad_hmac():
    """A garbage frame or a frame signed with the wrong key must fail
    loudly (connection poisoned, state untouched) instead of executing —
    the ADVICE.md pickle-RCE surface is gone."""
    from mxnet_tpu import kvstore_async as ka
    from mxnet_tpu.base import MXNetError

    os.environ["MXNET_PS_KEY"] = "ab" * 32
    try:
        port = _free_port()
        server = ka._PSServer("127.0.0.1", port, num_workers=1)
        try:
            # raw garbage bytes: server must refuse and close, not act
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(b"GET / HTTP/1.1\r\n\r\n" + b"\x00" * 64)
            s.settimeout(10)
            try:
                while s.recv(4096):  # drain err frame until clean close
                    pass
            except OSError:
                pass
            s.close()
            assert server._store == {}

            # correctly-formed frame, wrong key: rejected by HMAC
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            bad = ka._pack_frame(ka._OP_INIT, "w0",
                                 np.zeros(4, np.float32),
                                 secret=b"wrong-key-wrong-key-wrong-key-00")
            s.sendall(bad)
            try:
                op, _, _, arr = ka._recv_frame(s, secret=bytes.fromhex(
                    os.environ["MXNET_PS_KEY"]))
                assert op == ka._OP_ERR
            except (ConnectionError, MXNetError):
                pass  # poisoned connection is an acceptable loud failure
            s.close()
            assert server._store == {}, "bad frame mutated server state"
        finally:
            server.shutdown()
    finally:
        del os.environ["MXNET_PS_KEY"]


def test_dist_async_parameter_server_trains():
    """dist_async is a REAL hogwild parameter server (kvstore_async.py):
    rank 0 hosts it, pushes apply immediately with no worker barriers
    (reference kvstore_dist_server.h async branch), and training still
    converges on every rank."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", "2", "--launcher", "local", "--port", str(_free_port()),
        sys.executable, os.path.join(_ROOT, "tests", "dist_async_worker.py"),
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"async training failed:\n{out[-4000:]}"
    for r in range(2):
        assert f"rank {r}/2 ASYNC-TRAIN OK" in out, out[-4000:]
