"""Process-level distributed kvstore test.

Spawns real local processes through tools/launch.py --launcher local (the
reference's nightly tracker pattern) running tests/dist_worker.py, which
asserts exact reduction arithmetic across ranks — the port of
``tests/nightly/dist_sync_kvstore.py:22-58``.
"""

import os
import socket
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("nproc", [2, 3])
def test_dist_sync_kvstore_local_processes(nproc):
    env = dict(os.environ)
    # workers must initialise their own jax runtime on CPU, not inherit the
    # test process's virtual-device settings
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", str(nproc), "--launcher", "local",
        "--port", str(_free_port()),
        sys.executable, os.path.join(_ROOT, "tests", "dist_worker.py"),
    ]
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=300
    )
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, f"dist job failed:\n{out[-4000:]}"
    for r in range(nproc):
        assert f"rank {r}/{nproc} DIST OK" in out, out[-4000:]
