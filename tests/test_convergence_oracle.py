"""Deterministic convergence oracle (VERDICT weak item 6).

A fixed synthetic dataset + fixed seeds trains a small net; the per-epoch
cross-entropy trajectory is pinned against a recorded oracle. This guards
END-TO-END numerics (initializers → conv/FC forward → softmax backward →
momentum SGD → metric) the way the reference's trainer smoke tests pin
final accuracy (``tests/python/train/test_mlp.py``) — any silent numeric
regression in the stack shifts the trajectory.
"""

import numpy as np

import mxnet_tpu as mx

# recorded on the XLA:CPU backend (f32); per-epoch mean cross-entropy.
# Re-pinned after a jax/jaxlib toolchain bump shifted epoch 0 by ~0.04
# (verified bit-identical across repeat runs before re-recording).
_ORACLE = [0.267695, 0.107534, 0.088275, 0.034695, 0.022904, 0.015806,
           0.007040, 0.005197]


def _dataset():
    rng = np.random.RandomState(1234)
    n = 256
    t = rng.uniform(0, np.pi, n)
    cls = rng.randint(0, 2, n)
    X = np.stack([np.cos(t) + cls * 1.0, np.sin(t) * (1 - 2 * cls)], 1)
    X = (X + rng.randn(n, 2) * 0.15).astype(np.float32)
    return X, cls.astype(np.float32)


def test_training_trajectory_matches_oracle():
    X, Y = _dataset()
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="tanh",
    )
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=2, name="fc2"), name="softmax"
    )
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(X, Y, batch_size=32)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(99)
    mod.init_params(initializer=mx.init.Xavier(
        rnd_type="gaussian", factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    ce = mx.metric.CrossEntropy()
    traj = []
    for _ in range(len(_ORACLE)):
        it.reset()
        ce.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
            mod.update_metric(ce, b.label)
        traj.append(float(ce.get()[1]))
    # early epochs are numerically stable; late epochs sit in a flat
    # minimum where tiny float differences drift, so tolerance widens
    for i, (got, want) in enumerate(zip(traj, _ORACLE)):
        tol = 0.02 if i < 3 else 0.05
        assert abs(got - want) < tol, (
            f"epoch {i}: loss {got:.6f} deviates from oracle {want:.6f} "
            f"(full: {traj})"
        )
    assert traj[-1] < 0.08, f"did not converge: {traj}"
