"""Sharded serving tier (ISSUE 12): tp/pp inference on the GraftMesh
request path plus seq-len bucketed sequence serving.

Claims proven here, all on the virtual 8-device CPU mesh (conftest forces
``--xla_force_host_platform_device_count=8``):

- ``MXNET_SERVING_MESH=tp2`` partitions the 8 local devices into 4
  group-replicas of 2-device tensor-parallel sub-meshes; ``pp4`` into 2
  GPipe stage groups; ``auto`` keeps single-device replicas.
- Per-bucket sharded predictors serve with ZERO request-path XLA compiles
  after warmup (counter-verified), including across a hot reload.
- tp2 and pp2 outputs are BITWISE identical to a single-device reference
  per bucket (integer-lattice weights pin tp; pp needs no lattice — the
  stage split never re-associates a reduction).
- The PR-7 health/failover machinery composes unchanged over
  group-replicas: killing one group under traffic costs zero client
  errors.
- ``MXNET_SERVING_SEQ_BUCKETS`` serves variable-length sequences through
  per-(batch, seq-len)-bucket BucketingModule-style predictors from a
  ``sym_gen`` — the LSTM/PTB serving path, end-to-end over HTTP with
  per-bucket bitwise determinism.
- ``ModelRegistry`` hosts many models (``POST /predict/{model}``) with a
  deterministic canary split pinned via the weight-version response stamp
  and shadow duplication that never touches the primary answer.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faultinject
from mxnet_tpu import telemetry as tm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models import lstm_lm_serving_sym_gen
from mxnet_tpu.serving import (ModelRegistry, ModelServer, ServingConfig,
                               make_http_server, partition_devices)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    faultinject.reset()
    monkeypatch.delenv("MXNET_FI_SERVE_RAISE_REPLICA", raising=False)
    yield
    faultinject.reset()


def _delta(name):
    start = tm.counter(name).value
    return lambda: tm.counter(name).value - start


def _tp_mlp():
    """2-layer MLP with explicit tp shard annotations and an integer
    weight lattice: every dot-product term is an exact small float, so a
    2-way sharded matmul sums bitwise-identically to the unsharded one."""
    data = mx.sym.Variable("data")
    with mx.AttrScope(__shard__="tp:0"):
        w1 = mx.sym.Variable("fc1_weight")
    with mx.AttrScope(__shard__="tp:1"):
        w2 = mx.sym.Variable("fc2_weight")
    h = mx.sym.FullyConnected(data, weight=w1, num_hidden=16,
                              no_bias=True, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(h, weight=w2, num_hidden=4,
                                 no_bias=True, name="fc2")


def _tp_params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "fc1_weight": mx.nd.array(
            rng.randint(-3, 4, (16, 8)).astype(np.float32)),
        "fc2_weight": mx.nd.array(
            rng.randint(-3, 4, (4, 16)).astype(np.float32)),
    }


def _ref_out(params, x):
    ref = mx.predictor.Predictor(
        _tp_mlp(), {k: v.copy() for k, v in params.items()},
        {"data": (1, 8)}, fold_bn=False)
    return ref.run(data=x[None])[0][0]


# ---------------------------------------------------------------- mesh


def test_partition_devices_specs():
    import jax

    devs = jax.devices("cpu")
    assert len(devs) == 8
    tp2 = partition_devices("tp2", devs)
    assert len(tp2) == 4
    assert all(g.mesh.devices.size == 2 and g.tp == 2 for g in tp2)
    # partition is exhaustive and disjoint
    flat = [d for g in tp2 for d in g.mesh.devices.flat]
    assert sorted(d.id for d in flat) == [d.id for d in devs]
    pp4 = partition_devices("pp4", devs)
    assert len(pp4) == 2
    assert all(g.pp == 4 for g in pp4)
    # a non-dividing spec drops the partial tail group (documented), and
    # a spec larger than the device count is refused outright
    assert len(partition_devices("tp3", devs)) == 2
    with pytest.raises(MXNetError):
        partition_devices("tp16", devs)


def test_tp2_server_group_replicas_parity_and_no_compile():
    params = _tp_params()
    cfg = ServingConfig(buckets=(1, 4), mesh="tp2", fold_bn=False,
                        max_delay_ms=1.0)
    srv = ModelServer(_tp_mlp(), dict(params), {"data": (8,)}, config=cfg)
    assert len(srv.replicas) == 4
    assert all(r.mesh is not None and r.mesh.tp == 2 for r in srv.replicas)
    # device() names the group, not one device
    assert all(r.device().startswith("tp2[") for r in srv.replicas)
    srv.warmup()
    compiles = _delta("executor.jit_compile")
    rng = np.random.RandomState(3)
    x = rng.randint(-2, 3, (8,)).astype(np.float32)
    with srv:
        out = srv.predict({"data": x})
        out2 = srv.predict({"data": x})
    assert compiles() == 0, "request path compiled after warmup"
    assert np.array_equal(out[0], out2[0]), "tp2 serving not deterministic"
    assert np.array_equal(out[0], _ref_out(params, x)), (
        "tp2 output not bitwise-equal to the single-device reference")


def test_pp2_server_no_compile_across_reload_and_parity():
    params = _tp_params()
    cfg = ServingConfig(buckets=(1, 4), mesh="pp2", fold_bn=False,
                        max_delay_ms=1.0)
    srv = ModelServer(_tp_mlp(), dict(params), {"data": (8,)}, config=cfg)
    assert len(srv.replicas) == 4
    assert all(r.mesh.pp == 2 for r in srv.replicas)
    srv.warmup()
    compiles = _delta("executor.jit_compile")
    rng = np.random.RandomState(4)
    x = rng.randint(-2, 3, (8,)).astype(np.float32)
    params2 = {k: v * 2 for k, v in params.items()}
    with srv:
        out = srv.predict({"data": x})
        srv.reload({k: v.copy() for k, v in params2.items()})
        out2 = srv.predict({"data": x})
    # a weight swap must reuse the compiled per-bucket executables
    assert compiles() == 0, "reload or request path compiled"
    assert np.array_equal(out[0], _ref_out(params, x))
    assert np.array_equal(out2[0], _ref_out(params2, x)), (
        "post-reload pp2 output diverged from new-weight reference")


def test_group_replica_failover_under_chaos(monkeypatch):
    """Kill one tp2 GROUP under concurrent traffic: failover re-dispatch
    absorbs it with zero client-visible errors — the PR-7 machinery
    composes unchanged over device groups."""
    failover = _delta("serving.replica.failover")
    params = _tp_params()
    cfg = ServingConfig(buckets=(1, 4), mesh="tp2", fold_bn=False,
                        max_delay_ms=1.0, cb_probe_ms=60_000)
    rng = np.random.RandomState(5)
    xs = [rng.randint(-2, 3, (8,)).astype(np.float32) for _ in range(8)]
    with ModelServer(_tp_mlp(), dict(params), {"data": (8,)},
                     config=cfg) as srv:
        failures, done = [], []
        barrier = threading.Barrier(9)

        def client(cid):
            for i in range(4):
                try:
                    out = srv.predict({"data": xs[cid]}, timeout=60)
                    assert np.array_equal(out[0], _ref_out(params, xs[cid]))
                    done.append(1)
                except Exception as e:  # noqa: BLE001 — the assertion
                    failures.append(repr(e))
                if i == 0:
                    barrier.wait(timeout=60)

        def killer():
            barrier.wait(timeout=60)
            monkeypatch.setenv("MXNET_FI_SERVE_RAISE_REPLICA", "0")

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(8)] + [threading.Thread(target=killer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures, failures[:5]
        assert len(done) == 8 * 4
        assert failover() >= 1, "no batch ever failed over"
        states = {r["id"]: r["state"] for r in srv.stats()["replicas"]}
        assert states[0] == "open"


# ------------------------------------------------------- seq buckets


def _lstm_setup(V=50, H=16, E=12, seed=7):
    sym_gen = lstm_lm_serving_sym_gen(num_hidden=H, num_layers=1,
                                      num_embed=E, vocab_size=V)
    probe, _, _ = sym_gen(4)
    tmp = mx.predictor.Predictor(probe, {}, {"data": (2, 4)},
                                 fold_bn=False,
                                 input_types={"data": "int32"})
    rng = np.random.RandomState(seed)
    params = {
        name: mx.nd.array(
            rng.uniform(-0.1, 0.1, arr.shape).astype(np.float32))
        for name, arr in tmp._exec.arg_dict.items()
        if name != "data" and "begin_state" not in name
    }
    return sym_gen, params, rng


def test_seq_bucketed_lstm_server():
    V = 50
    sym_gen, params, rng = _lstm_setup(V=V)
    cfg = ServingConfig(buckets=(1, 2), seq_buckets=(4, 8), fold_bn=False,
                        max_delay_ms=1.0, replicas=1)
    srv = ModelServer(None, dict(params), {"data": (8,)}, config=cfg,
                      input_types={"data": "int32"}, sym_gen=sym_gen)
    # one BucketingModule-style predictor per (batch, seq) bucket
    assert sorted(srv._predictors) == [(1, 4), (1, 8), (2, 4), (2, 8)]
    srv.warmup()
    compiles = _delta("executor.jit_compile")
    x3 = rng.randint(0, V, (3,)).astype(np.int32)
    x8 = rng.randint(0, V, (8,)).astype(np.int32)
    with srv:
        o3 = srv.predict({"data": x3})   # pads to seq bucket 4
        o3b = srv.predict({"data": x3})
        o8 = srv.predict({"data": x8})
        # an over-long request is refused, not silently truncated
        with pytest.raises(MXNetError):
            srv.predict({"data": rng.randint(0, V, (9,)).astype(np.int32)})
    assert compiles() == 0, "seq-bucket request path compiled after warmup"
    assert o3[0].shape == (4, V)  # padded to the seq bucket
    assert o8[0].shape == (8, V)
    assert np.array_equal(o3[0], o3b[0]), "seq serving not deterministic"
    # parity vs a direct predictor on the padded bucket shape
    p = mx.predictor.Predictor(
        sym_gen(4)[0], {k: v.copy() for k, v in params.items()},
        {"data": (1, 4)}, fold_bn=False, input_types={"data": "int32"})
    xp = np.zeros((1, 4), np.int32)
    xp[0, :3] = x3
    assert np.array_equal(o3[0], p.run(data=xp)[0][0])


def test_sym_gen_requires_seq_buckets():
    sym_gen, params, _ = _lstm_setup()
    with pytest.raises(MXNetError):
        ModelServer(None, dict(params), {"data": (8,)},
                    config=ServingConfig(buckets=(1,), fold_bn=False),
                    input_types={"data": "int32"}, sym_gen=sym_gen)


# ------------------------------------------------- registry + HTTP


def _mlp_plain():
    data = mx.sym.Variable("data")
    return mx.sym.FullyConnected(data, num_hidden=8, no_bias=True,
                                 name="fc1")


def test_registry_canary_split_and_http_e2e():
    rng = np.random.RandomState(0)
    params = {"fc1_weight": mx.nd.array(
        rng.randint(-3, 4, (8, 4)).astype(np.float32))}
    params2 = {"fc1_weight": params["fc1_weight"] * 2}

    def cfg():
        return ServingConfig(buckets=(1, 4), replicas=1, fold_bn=False,
                             max_delay_ms=0.5)

    primary = ModelServer(_mlp_plain(), dict(params), {"data": (4,)},
                          config=cfg())
    canary = ModelServer(_mlp_plain(), dict(params2), {"data": (4,)},
                         config=cfg())
    # a reload bumps the canary's replica version to 1: the response
    # stamp (set under the replica lock) then tells the tracks apart
    canary.reload(dict(params2))

    V = 30
    sym_gen, lp, _ = _lstm_setup(V=V, H=8, E=6)
    lstm = ModelServer(None, lp, {"data": (8,)},
                       config=ServingConfig(buckets=(1, 2),
                                            seq_buckets=(4, 8), replicas=1,
                                            fold_bn=False,
                                            max_delay_ms=0.5),
                       input_types={"data": "int32"}, sym_gen=sym_gen)

    reg = ModelRegistry()
    reg.register("mlp", primary, canary=canary, canary_pct=50.0)
    reg.register("lm", lstm)
    reg.start()
    httpd = make_http_server(reg, host="127.0.0.1", port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()

    def post(path, payload):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    try:
        # deterministic 50% split: the accumulator routes request
        # 2, 4, 6 to the canary — stamps alternate exactly
        x = [1.0, 2.0, 3.0, 4.0]
        stamps = [post("/predict/mlp", {"inputs": {"data": x}})["version"]
                  for _ in range(6)]
        assert stamps == [0, 1, 0, 1, 0, 1], stamps

        # LSTM seq-bucketed serving end-to-end over HTTP, bitwise
        # deterministic per bucket
        toks = [3, 7, 11]
        r1 = post("/predict/lm", {"inputs": {"data": toks}})
        r2 = post("/predict/lm", {"inputs": {"data": toks}})
        assert r1["shapes"] == [[4, V]]
        assert r1["outputs"] == r2["outputs"]

        # aggregate health + per-model labeled metrics
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as r:
            hz = json.loads(r.read())
        assert sorted(hz["models"]) == ["lm", "mlp"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as r:
            m = r.read().decode()
        assert 'mxnet_serving_model_requests_total{model="mlp"} 6' in m
        assert 'mxnet_serving_model_version{model="mlp",track="canary"} 1' \
            in m

        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/predict/nope", {"inputs": {"data": x}})
        assert ei.value.code == 404
    finally:
        httpd.shutdown()
        httpd.server_close()
        reg.close()


def test_registry_shadow_never_touches_primary_answer():
    rng = np.random.RandomState(0)
    params = {"fc1_weight": mx.nd.array(
        rng.randint(-3, 4, (8, 4)).astype(np.float32))}
    params2 = {"fc1_weight": params["fc1_weight"] * 2}

    def cfg():
        return ServingConfig(buckets=(1, 4), replicas=1, fold_bn=False,
                             max_delay_ms=0.5)

    reg = ModelRegistry()
    reg.register("m",
                 ModelServer(_mlp_plain(), dict(params), {"data": (4,)},
                             config=cfg()),
                 canary=ModelServer(_mlp_plain(), dict(params2),
                                    {"data": (4,)}, config=cfg()),
                 canary_pct=0.0, shadow=True)
    with reg:
        x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        outs = reg.predict("m", {"data": x})
        ref = mx.predictor.Predictor(
            _mlp_plain(), dict(params), {"data": (1, 4)},
            fold_bn=False).run(data=x[None])
        assert np.array_equal(outs[0], ref[0][0]), (
            "shadow mode changed the primary answer")
        st = reg.stats()["models"]["m"]
        assert st["requests"] == 1
        assert st["canary_routed"] == 0
        assert st["shadow_errors"] == 0
