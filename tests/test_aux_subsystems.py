"""Aux subsystems: env catalogue, NaiveEngine, profiler contract, monitor,
predictor, FeedForward, visualization, remat — the previously untested
surface (VERDICT weak item 8 + env/profiler items).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


# --------------------------------------------------------------------------
# env catalogue
# --------------------------------------------------------------------------
def test_env_catalogue_document_and_get():
    doc = mx.env.document()
    assert "MXNET_ENGINE_TYPE" in doc and "| Default |" in doc
    assert mx.env.get("MXNET_NUM_PROCS") >= 1
    os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "123"
    try:
        assert mx.env.get("MXNET_KVSTORE_BIGARRAY_BOUND") == 123
    finally:
        del os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"]


def test_env_check_unknown():
    os.environ["MXNET_NOT_A_REAL_VAR"] = "1"
    try:
        assert "MXNET_NOT_A_REAL_VAR" in mx.env.check_unknown()
    finally:
        del os.environ["MXNET_NOT_A_REAL_VAR"]


# --------------------------------------------------------------------------
# engine facade + storage stats
# --------------------------------------------------------------------------
def test_engine_facade():
    eng = mx.engine.get()
    assert eng is mx.engine.get()  # singleton
    assert isinstance(eng.type, str)
    a = mx.nd.ones((4,)) * 3
    var = eng.new_variable()
    var.attach(a)
    ran = []
    eng.push(lambda: ran.append(float(a.asnumpy().sum())), read_vars=[var])
    assert ran == [12.0]
    eng.wait_for_var(var)
    eng.wait_for_all()
    # set_bulk_size returns the PREVIOUS size (reference semantics) and 0
    # genuinely disables the fused train step via the env toggle
    prev = eng.set_bulk_size(0)
    assert os.environ["MXNET_EXEC_BULK_EXEC_TRAIN"] == "0"
    assert eng.set_bulk_size(prev) == 0
    assert os.environ["MXNET_EXEC_BULK_EXEC_TRAIN"] == "1"


def test_context_memory_stats():
    stats = mx.cpu().memory_stats()
    assert isinstance(stats, dict)  # keys backend-defined; may be empty


def test_v1_op_aliases():
    """Legacy *_v1 twins resolve to the modern layers (reference
    convolution_v1/pooling_v1/batch_norm_v1 registrations)."""
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution_v1(data, num_filter=2, kernel=(3, 3), name="c")
    p = mx.sym.Pooling_v1(c, kernel=(2, 2), stride=(2, 2), pool_type="max")
    exe = p.simple_bind(mx.cpu(), data=(1, 2, 8, 8))
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = mx.nd.ones(a.shape) * 0.1
    exe.arg_dict["data"][:] = mx.nd.ones((1, 2, 8, 8))
    out = exe.forward()[0]
    assert out.shape == (1, 2, 3, 3)


# --------------------------------------------------------------------------
# NaiveEngine sync-debug toggle (reference engine.cc:14-27)
# --------------------------------------------------------------------------
def test_naive_engine_matches_default():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)

    def run():
        mx.random.seed(11)
        sym = _mlp()
        exe = sym.simple_bind(mx.cpu(), data=(4, 6), softmax_label=(4,))
        mx.random.seed(12)
        ini = mx.init.Xavier()
        for n, a in exe.arg_dict.items():
            if n not in ("data", "softmax_label"):
                ini(mx.init.InitDesc(n), a)
        exe.arg_dict["data"][:] = mx.nd.array(x)
        exe.arg_dict["softmax_label"][:] = mx.nd.array(np.zeros(4, np.float32))
        exe.forward(is_train=True)
        out = exe.outputs[0].asnumpy()
        exe.backward()
        return out, exe.grad_dict["fc1_weight"].asnumpy()

    base_out, base_grad = run()
    os.environ["MXNET_ENGINE_TYPE"] = "NaiveEngine"
    try:
        naive_out, naive_grad = run()
    finally:
        del os.environ["MXNET_ENGINE_TYPE"]
    assert_almost_equal(base_out, naive_out, rtol=1e-5, atol=1e-6)
    assert_almost_equal(base_grad, naive_grad, rtol=1e-5, atol=1e-5)


def test_bulk_exec_toggle_trains_identically():
    rng = np.random.RandomState(1)
    X = rng.randn(16, 6).astype(np.float32)
    Y = (rng.rand(16) * 3).astype(np.float32)

    def train():
        mx.random.seed(5)
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        mod.bind(data_shapes=[("data", (8, 6))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params(initializer=mx.init.Xavier(), force_init=True)
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1},
                           force_init=True)
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        for _ in range(3):
            it.reset()
            for b in it:
                mod.forward_backward(b)
                mod.update()
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    fused = train()
    os.environ["MXNET_EXEC_BULK_EXEC_TRAIN"] = "0"
    try:
        unfused = train()
    finally:
        del os.environ["MXNET_EXEC_BULK_EXEC_TRAIN"]
    for k in fused:
        assert_almost_equal(fused[k], unfused[k], rtol=1e-4, atol=1e-5,
                            names=(f"fused:{k}", f"unfused:{k}"))


# --------------------------------------------------------------------------
# executor rng honours the global seed (ADVICE item)
# --------------------------------------------------------------------------
def test_symbolic_dropout_respects_global_seed():
    data = mx.sym.Variable("data")
    sym = mx.sym.Dropout(data, p=0.5)
    x = np.ones((16, 16), np.float32)

    def mask(seed_v):
        mx.random.seed(seed_v)
        exe = sym.bind(mx.cpu(), args={"data": mx.nd.array(x)})
        exe.forward(is_train=True)
        return exe.outputs[0].asnumpy()

    a, b = mask(1), mask(1)
    c = mask(2)
    assert_almost_equal(a, b)
    assert np.abs(a - c).max() > 0, "different seeds gave identical dropout"


# --------------------------------------------------------------------------
# backward without out_grads (ADVICE item)
# --------------------------------------------------------------------------
def test_backward_requires_loss_or_out_grads():
    data = mx.sym.Variable("data")
    sym = data * 2.0  # no loss head
    exe = sym.bind(mx.cpu(), args={"data": mx.nd.ones((2, 2))},
                   args_grad={"data": mx.nd.zeros((2, 2))})
    exe.forward(is_train=True)
    exe.backward()
    with pytest.raises(mx.MXNetError, match="loss"):
        exe.grad_dict["data"].asnumpy()  # materialisation surfaces the error


def test_backward_group_ignores_non_loss_heads():
    """Group(loss, features): implicit backward must not inject gradients
    from the feature head (ADVICE executor.py:262)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    feat = data * 3.0
    loss = mx.sym.LinearRegressionOutput(feat, label, name="lro")
    group = mx.sym.Group([loss, feat])
    x = np.array([[1.0, 2.0]], np.float32)
    y = np.array([[0.0, 0.0]], np.float32)
    exe = group.bind(
        mx.cpu(),
        args={"data": mx.nd.array(x), "label": mx.nd.array(y)},
        args_grad={"data": mx.nd.zeros((1, 2))},
        grad_req={"data": "write", "label": "null"},
    )
    exe.forward(is_train=True)
    exe.backward()
    # d(loss)/d(data) only: (pred-label)/num_output * d(feat)/d(data)
    expect = (3 * x - y) / x.shape[1] * 3.0
    assert_almost_equal(exe.grad_dict["data"].asnumpy(), expect, rtol=1e-4)


# --------------------------------------------------------------------------
# FC flatten=False (ADVICE item)
# --------------------------------------------------------------------------
def test_fc_flatten_false():
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data, num_hidden=5, flatten=False, name="fc",
                                no_bias=True)
    x = np.random.RandomState(0).randn(2, 3, 4).astype(np.float32)
    w = np.random.RandomState(1).randn(5, 4).astype(np.float32)
    exe = sym.bind(mx.cpu(), args={"data": mx.nd.array(x),
                                   "fc_weight": mx.nd.array(w)})
    out = exe.forward()[0].asnumpy()
    assert out.shape == (2, 3, 5)
    assert_almost_equal(out, x.dot(w.T), rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# profiler file contract
# --------------------------------------------------------------------------
def test_profiler_dump_writes_chrome_trace(tmp_path):
    fname = str(tmp_path / "prof.json")
    mx.profiler.profiler_set_config(mode="all", filename=fname)
    mx.profiler.profiler_set_state("run")
    (mx.nd.ones((64, 64)) * 2).wait_to_read()
    mx.profiler.profiler_set_state("stop")
    out = mx.profiler.dump_profile()
    assert out == fname and os.path.exists(fname)
    with open(fname) as f:
        trace = json.load(f)
    assert "traceEvents" in trace and len(trace["traceEvents"]) > 0


# --------------------------------------------------------------------------
# monitor
# --------------------------------------------------------------------------
def test_monitor_all_reports_variables():
    """monitor_all=True additionally streams weights/data/aux through the
    callback during the pass itself (reference SetMonitorCallbackEX)."""
    seen = []
    sym = _mlp()
    exe = sym.simple_bind(mx.cpu(), data=(2, 6), softmax_label=(2,))
    for n, a in exe.arg_dict.items():
        if n not in ("data", "softmax_label"):
            a[:] = mx.nd.ones(a.shape) * 0.1
    exe.set_monitor_callback(lambda n, a: seen.append(n), monitor_all=True)
    exe.forward(is_train=True)
    assert "fc1_weight" in seen and "data" in seen  # inputs reported
    assert any(n.endswith("_output") for n in seen)
    seen.clear()
    exe.set_monitor_callback(lambda n, a: seen.append(n), monitor_all=False)
    exe.forward(is_train=True)
    assert "fc1_weight" not in seen  # outputs only without monitor_all
    assert any(n.endswith("_output") for n in seen)


def test_monitor_collects_stats():
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc1.*")
    sym = _mlp()
    exe = sym.simple_bind(mx.cpu(), data=(2, 6), softmax_label=(2,))
    for n, a in exe.arg_dict.items():
        if n not in ("data", "softmax_label"):
            a[:] = mx.nd.ones(a.shape) * 0.1
    mon.install(exe)
    mon.tic()
    exe.arg_dict["data"][:] = mx.nd.ones((2, 6))
    exe.forward(is_train=True)
    rows = mon.toc()
    names = [r[1] for r in rows]
    assert any("fc1_output" in n for n in names)
    assert any(n == "fc1_weight" for n in names)  # param sweep in toc
    assert all(isinstance(r[2], str) for r in rows)


# --------------------------------------------------------------------------
# callbacks
# --------------------------------------------------------------------------
def test_speedometer_logs_rate(caplog):
    import logging as _logging
    from collections import namedtuple

    P = namedtuple("P", ["epoch", "nbatch", "eval_metric"])
    spd = mx.callback.Speedometer(batch_size=32, frequent=2)
    with caplog.at_level(_logging.INFO):
        for nb in range(1, 7):
            spd(P(0, nb, None))
    msgs = [r.message for r in caplog.records if "Speed" in r.message]
    # fires at nbatch 2 (arms), 4, 6 → two rate logs
    assert len(msgs) == 2
    assert "samples/sec" in msgs[0]


def test_checkpoint_callbacks_fire_on_period(tmp_path):
    fired = []

    class FakeMod:
        def save_checkpoint(self, prefix, epoch, states=False):
            fired.append(epoch)

    cb = mx.callback.module_checkpoint(FakeMod(), str(tmp_path / "p"), period=2)
    for ep in range(4):
        cb(ep)
    assert fired == [2, 4]


# --------------------------------------------------------------------------
# predictor + FeedForward + visualization
# --------------------------------------------------------------------------
def test_predictor_api(tmp_path):
    sym = _mlp()
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "pred")
    mod.save_checkpoint(prefix, 0)
    with open(prefix + "-symbol.json") as f:
        symbol_json = f.read()
    with open(prefix + "-0000.params", "rb") as f:
        param_bytes = f.read()
    pred = mx.predictor.Predictor(
        symbol_json, param_bytes, {"data": (2, 6)}
    )
    x = np.random.RandomState(0).rand(2, 6).astype(np.float32)
    pred.forward(data=x)
    out = pred.get_output(0)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], []), is_train=False)
    assert_almost_equal(out, mod.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_feedforward_fit_predict():
    rng = np.random.RandomState(3)
    X = rng.randn(32, 6).astype(np.float32)
    W = rng.randn(6, 3).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)
    model = mx.model.FeedForward(
        symbol=_mlp(), ctx=mx.cpu(), num_epoch=6,
        optimizer="sgd", learning_rate=0.3,
        initializer=mx.init.Xavier(),
    )
    model.fit(X=mx.io.NDArrayIter(X, Y, batch_size=8))
    prob = model.predict(mx.io.NDArrayIter(X, batch_size=8))
    acc = (prob.argmax(1) == Y).mean()
    assert acc > 0.8, f"FeedForward did not learn: {acc}"


def test_visualization_summary_and_plot():
    sym = _mlp()
    txt = mx.viz.print_summary(sym, shape={"data": (1, 6)})
    assert txt is None or isinstance(txt, str)  # prints; must not raise
    try:
        g = mx.viz.plot_network(sym, shape={"data": (1, 6)})
        assert g is not None
    except ImportError:
        pass  # graphviz not installed — acceptable


# --------------------------------------------------------------------------
# remat (MXNET_BACKWARD_DO_MIRROR)
# --------------------------------------------------------------------------
def test_backward_mirror_same_numerics():
    rng = np.random.RandomState(4)
    x = rng.randn(4, 6).astype(np.float32)

    def run():
        sym = _mlp()
        exe = sym.simple_bind(mx.cpu(), data=(4, 6), softmax_label=(4,))
        mx.random.seed(9)
        ini = mx.init.Xavier()
        for n, a in exe.arg_dict.items():
            if n not in ("data", "softmax_label"):
                ini(mx.init.InitDesc(n), a)
        exe.arg_dict["data"][:] = mx.nd.array(x)
        exe.arg_dict["softmax_label"][:] = mx.nd.array(np.zeros(4, np.float32))
        exe.forward(is_train=True)
        exe.backward()
        return exe.grad_dict["fc2_weight"].asnumpy()

    base = run()
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1"
    try:
        mirrored = run()
    finally:
        del os.environ["MXNET_BACKWARD_DO_MIRROR"]
    assert_almost_equal(base, mirrored, rtol=1e-5, atol=1e-6)
