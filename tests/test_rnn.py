"""RNN cell tests (reference test_rnn.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(10, prefix="rnn_")
    outputs, states = cell.unroll(3)
    outs = mx.sym.Group(outputs)
    args = sorted(set(outs.list_arguments()))
    assert "rnn_i2h_weight" in args and "rnn_h2h_weight" in args
    arg_shapes, out_shapes, _ = outs.infer_shape(
        t0_data=(2, 6), t1_data=(2, 6), t2_data=(2, 6),
        rnn_begin_state_0=(2, 10),
    )
    assert out_shapes == [(2, 10)] * 3


def test_lstm_cell_forward():
    cell = mx.rnn.LSTMCell(4, prefix="lstm_", forget_bias=0.0)
    x = mx.sym.Variable("x")
    h0 = mx.sym.Variable("h0")
    c0 = mx.sym.Variable("c0")
    out, states = cell(x, [h0, c0])
    rs = np.random.RandomState(0)
    xv = rs.randn(1, 3).astype(np.float32)
    h0v = np.zeros((1, 4), dtype=np.float32)
    c0v = np.zeros((1, 4), dtype=np.float32)
    wi = rs.randn(16, 3).astype(np.float32)
    bi = np.zeros(16, dtype=np.float32)
    wh = rs.randn(16, 4).astype(np.float32)
    bh = np.zeros(16, dtype=np.float32)
    exe = out.bind(mx.cpu(), args={
        "x": mx.nd.array(xv), "h0": mx.nd.array(h0v), "c0": mx.nd.array(c0v),
        "lstm_i2h_weight": mx.nd.array(wi), "lstm_i2h_bias": mx.nd.array(bi),
        "lstm_h2h_weight": mx.nd.array(wh), "lstm_h2h_bias": mx.nd.array(bh),
    })
    exe.forward(is_train=False)
    # numpy LSTM oracle
    gates = xv @ wi.T + h0v @ wh.T
    i, f, c, o = np.split(gates, 4, axis=1)
    sig = lambda z: 1 / (1 + np.exp(-z))
    c_new = sig(f) * c0v + sig(i) * np.tanh(c)
    h_new = sig(o) * np.tanh(c_new)
    assert_almost_equal(exe.outputs[0].asnumpy(), h_new, rtol=1e-4, atol=1e-5)


def test_gru_cell_runs():
    cell = mx.rnn.GRUCell(5, prefix="gru_")
    outputs, _ = cell.unroll(2, input_prefix="g")
    outs = mx.sym.Group(outputs)
    exe = outs.simple_bind(
        ctx=mx.cpu(),
        **{"gt0_data": (2, 4), "gt1_data": (2, 4), "gru_begin_state_0": (2, 5)},
    )
    exe.forward(is_train=False)
    assert exe.outputs[0].shape == (2, 5)


def test_sequential_stack():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.LSTMCell(8, prefix="l1_"))
    outputs, states = stack.unroll(3)
    assert len(states) == 4  # 2 states per LSTM layer
    outs = mx.sym.Group(outputs)
    args = outs.list_arguments()
    assert "l0_i2h_weight" in args and "l1_i2h_weight" in args


def test_bidirectional_cell():
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(4, prefix="l_"), mx.rnn.LSTMCell(4, prefix="r_"),
    )
    data = mx.sym.Variable("data")
    outputs, _ = cell.unroll(3, inputs=data, merge_outputs=False)
    outs = mx.sym.Group(outputs)
    shapes = {
        "data": (2, 3, 6),
        **{f"{p}_begin_state_{i}": (2, 4) for p in ("l", "r") for i in (0, 1)},
    }
    arg_shapes, out_shapes, _ = outs.infer_shape(**shapes)
    assert all(s == (2, 8) for s in out_shapes)  # concat of fwd+bwd


def test_dropout_residual_cells():
    base = mx.rnn.RNNCell(6, prefix="b_")
    res = mx.rnn.ResidualCell(base)
    x = mx.sym.Variable("x")
    states = res.begin_state()
    out, _ = res(x, states)
    arg_shapes, out_shapes, _ = out.infer_shape(
        x=(2, 6), b_begin_state_0=(2, 6)
    )
    assert out_shapes[0] == (2, 6)


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [2, 3], [1, 2, 3, 4, 5], [3, 4]] * 10
    it = mx.rnn.BucketSentenceIter(
        sentences, batch_size=4, buckets=[3, 5], invalid_label=0
    )
    batch = next(iter(it))
    assert batch.bucket_key in (3, 5)
    assert batch.data[0].shape[0] == 4
    assert batch.data[0].shape[1] == batch.bucket_key


def test_encode_sentences():
    sents, vocab = mx.rnn.encode_sentences(
        [["a", "b"], ["b", "c"]], start_label=1
    )
    assert len(vocab) >= 3
    assert sents[0][1] == sents[1][0]  # same token 'b' → same id
