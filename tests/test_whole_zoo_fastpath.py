"""Whole-zoo fast path (ISSUE 13): every BASELINE workload through the
modern stack.

Pins, per workload, the two invariants the scoreboard advertises —
counter-verified on the framework's own telemetry, mirroring
tests/test_async_pipeline.py:

* ZERO steady-state compiles: once a workload's programs are warm,
  ``executor.jit_compile`` (AOT forward/train-step builds) and
  ``executor.fused_plan_compile`` (fused-window plan builds) both stay 0.
  The warmup phase must show ``fused_plan_compile > 0`` first — a counter
  that never fires would make the steady-state assert vacuous.
* ZERO per-batch host syncs: ``ndarray.asnumpy`` / ``wait_to_read`` /
  ``metric.numpy_fallback`` / ``metric.drain_sync`` do not scale with
  batches in the steady state.

Plus the numerical anchors: the fused DCGAN step bit-matches the
reference imperative loop after one adam step, the FLOPs estimator
reproduces its closed forms (MAC convention), and the zoo registry covers
the published 14-symbol table.
"""

import os
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402
from mxnet_tpu import telemetry as tm  # noqa: E402

_SYNC_COUNTERS = ("ndarray.asnumpy", "ndarray.wait_to_read",
                  "metric.numpy_fallback", "metric.drain_sync")


def _sync_counts():
    return {name: tm.counter(name).value for name in _SYNC_COUNTERS}


def _compiles():
    return (tm.counter("executor.jit_compile").value,
            tm.counter("executor.fused_plan_compile").value)


# ---------------------------------------------------------------------------
# bucketed LSTM-PTB


def _lstm_fixture(bs=4, hidden=16, vocab=50, buckets=(6, 10), k=2):
    rs = np.random.RandomState(0)
    sents = [[int(x) for x in rs.randint(1, vocab, int(rs.choice(buckets)))]
             for _ in range(bs * 4)]
    it = mx.rnn.BucketSentenceIter(sents, bs, buckets=list(buckets),
                                   invalid_label=0)
    sym_gen, state_names = models.lstm_lm_sym_gen(
        num_hidden=hidden, num_layers=1, num_embed=hidden, vocab_size=vocab)
    mod = mx.mod.BucketingModule(sym_gen=sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 state_names=state_names, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batches = list(it)
    chunks = [batches[i:i + k] for i in range(0, len(batches), k)]
    return mod, chunks


def test_bucketed_lstm_zero_steady_compiles_zero_syncs():
    """After one warmup epoch over the bucket mix, a steady epoch of
    grouped K-batch windows issues no compiles and no per-batch host
    syncs — switch_bucket is a pure cache pick."""
    mod, chunks = _lstm_fixture()
    tm.reset()
    for ch in chunks:
        mod.train_window(None, batches=ch, publish_grads=False).wait()
    jit_warm, plan_warm = _compiles()
    # the warmup epoch proves the compile counter fires (one fused plan
    # per (bucket, group size) pair) — without this the steady assert
    # below could pass vacuously with a dead counter
    assert plan_warm > 0
    windows_warm = tm.counter("bucketing.window").value
    assert windows_warm > 0

    tm.reset()
    for ch in chunks:
        mod.train_window(None, batches=ch, publish_grads=False).wait()
    jit_steady, plan_steady = _compiles()
    assert (jit_steady, plan_steady) == (0, 0), (
        f"steady-state epoch recompiled: jit={jit_steady} "
        f"fused_plan={plan_steady}")
    assert tm.counter("executor.fused_plan_hit").value == windows_warm
    assert tm.counter("bucketing.window").value == windows_warm
    assert _sync_counts() == {name: 0 for name in _SYNC_COUNTERS}, (
        _sync_counts())


# ---------------------------------------------------------------------------
# DCGAN


_GAN_BS, _GAN_Z, _GAN_NF = 4, 8, 4


def _gan_fixture(seed=7):
    mx.random.seed(seed)
    gan = mx.mod.GANModule(
        models.dcgan_generator(ngf=_GAN_NF, nc=3),
        models.dcgan_discriminator(ndf=_GAN_NF),
        context=mx.cpu(), batch_size=_GAN_BS, code_shape=(_GAN_Z, 1, 1),
        data_shape=(3, 64, 64))
    gan.bind()
    gan.init_params()
    gan.init_optimizer()
    return gan


def _gan_state(gan):
    state = {}
    for tag, mod in (("g", gan.mod_g), ("d", gan.mod_d)):
        exe = mod._exec_group._exec
        inputs = set(mod.data_names) | set(mod.label_names or ())
        for n, v in exe.arg_dict.items():
            if n in inputs:  # data/label slots, not trained state
                continue
            state[f"{tag}.{n}"] = np.asarray(v._data, np.float32)
        for n, v in exe.aux_dict.items():
            state[f"{tag}.aux.{n}"] = np.asarray(v._data, np.float32)
    return state


def test_dcgan_fused_step_matches_reference_loop():
    """One fused G/D step under pinned latents reproduces the reference
    imperative loop's weights, aux state and published outputs (adam at
    t=1 is sign-SGD-like, so any ordering bug amplifies to full +/-lr
    weight diffs — exact agreement here pins the whole step ordering)."""
    rng = np.random.RandomState(3)
    real_np = (rng.rand(_GAN_BS, 3, 64, 64).astype(np.float32) * 2 - 1)
    lat_np = rng.randn(_GAN_BS, _GAN_Z, 1, 1).astype(np.float32)

    gan_f = _gan_fixture()
    b_f = gan_f.train_window(mx.nd.array(real_np),
                             latents=[mx.nd.array(lat_np)])
    fused = _gan_state(gan_f)
    outs_f = [o.asnumpy() for o in b_f.outputs]

    gan_s = _gan_fixture()
    b_s = gan_s._serial_window([mx.nd.array(real_np)],
                               [mx.nd.array(lat_np)])
    serial = _gan_state(gan_s)
    outs_s = [o.asnumpy() for o in b_s.outputs]

    assert fused.keys() == serial.keys()
    for key in fused:
        np.testing.assert_allclose(fused[key], serial[key], rtol=1e-4,
                                   atol=1e-5, err_msg=key)
    for a, b in zip(outs_f, outs_s):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_dcgan_steady_windows_zero_compiles_zero_syncs():
    gan = _gan_fixture()
    real = mx.nd.array(
        np.random.RandomState(5).rand(_GAN_BS, 3, 64, 64).astype(np.float32))
    tm.reset()
    gan.train_window(real, 2).wait()
    _, plan_warm = _compiles()
    assert plan_warm > 0  # the plan-compile counter fires on warmup

    tm.reset()
    for _ in range(3):
        gan.train_window(real, 2).wait()
    assert _compiles() == (0, 0)
    assert tm.counter("executor.fused_plan_hit").value == 3
    assert tm.counter("gan.window").value == 3
    assert _sync_counts() == {name: 0 for name in _SYNC_COUNTERS}, (
        _sync_counts())


# ---------------------------------------------------------------------------
# SSD through fit's window branch


def _mini_ssd_train_sym(num_classes=2):
    """The SSD loss head (multibox_layer → MultiBoxTarget → multi-loss
    Group, verbatim from models/ssd.py's get_symbol_train tail) on a
    3-conv trunk: the fit-window invariants exercise the SAME detection
    path — in-graph target assignment, hard negative mining, the Group of
    heterogeneous losses — without the VGG16 compile bill, which the
    bench suite smoke already pays for the real SSD-VGG16."""
    s = mx.sym
    body = s.Variable("data")
    feats = []
    for i, nf in enumerate((8, 16, 32)):
        body = s.Activation(
            s.Convolution(body, num_filter=nf, kernel=(3, 3),
                          stride=(2, 2), pad=(1, 1), name=f"trunk_{i}"),
            act_type="relu")
        feats.append(body)
    loc_preds, cls_preds, anchor_boxes = models.ssd.multibox_layer(
        feats[-2:], num_classes,
        sizes=[(0.2, 0.272), (0.54, 0.619)],
        ratios=[(1, 2, 0.5), (1, 2, 0.5)])
    tmp = s.MultiBoxTarget(
        anchor_boxes, s.Variable("label"), cls_preds,
        overlap_threshold=0.5, ignore_label=-1, negative_mining_ratio=3,
        minimum_negative_samples=0, negative_mining_thresh=0.5,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
    cls_prob = s.SoftmaxOutput(
        cls_preds, tmp[2], ignore_label=-1, use_ignore=True,
        multi_output=True, normalization="valid", name="cls_prob")
    loc_loss = s.MakeLoss(
        s.smooth_l1(tmp[1] * (loc_preds - tmp[0]), scalar=1.0,
                    name="loc_loss_"),
        grad_scale=1.0, normalization="valid", name="loc_loss")
    cls_label = s.MakeLoss(tmp[2], grad_scale=0.0, name="cls_label")
    return s.Group([cls_prob, loc_loss, cls_label])


def test_ssd_fit_window_branch_no_steady_syncs(monkeypatch):
    """The multi-loss SSD Group rides fit's fused-window pipeline: the
    steady epoch (after the compile epoch is discarded) must issue zero
    compiles and zero per-batch host syncs, with the device-resident Loss
    metric draining once per epoch."""
    monkeypatch.setenv("MXNET_TRAIN_WINDOW", "2")
    monkeypatch.setenv("MXNET_DISPATCH_DEPTH", "2")
    monkeypatch.setenv("MXNET_DEVICE_PREFETCH", "1")
    bs, size, max_obj = 2, 32, 3
    rng = np.random.RandomState(0)
    n = bs * 4
    data = rng.uniform(-1, 1, (n, 3, size, size)).astype(np.float32)
    label = np.full((n, max_obj, 5), -1.0, np.float32)
    for i in range(n):
        x1, y1 = rng.uniform(0, 0.4, 2)
        label[i, 0] = [rng.randint(0, 2), x1, y1, x1 + 0.4, y1 + 0.4]
    it = mx.io.NDArrayIter({"data": data}, {"label": label}, batch_size=bs,
                           last_batch_handle="discard")
    net = _mini_ssd_train_sym(num_classes=2)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())

    def epoch_cb(epoch, sym=None, arg=None, aux=None):
        if epoch == 0:
            tm.reset()  # discard the compile epoch, as bench fit does

    metric = mx.metric.Loss(name="ssd_loss")
    mod.fit(it, eval_metric=metric, optimizer="sgd",
            optimizer_params={"learning_rate": 0.002, "momentum": 0.9},
            initializer=mx.init.Xavier(), num_epoch=2,
            epoch_end_callback=epoch_cb)
    assert _compiles() == (0, 0), "steady SSD epoch recompiled"
    counts = _sync_counts()
    assert counts["ndarray.asnumpy"] == 0
    assert counts["ndarray.wait_to_read"] == 0
    assert counts["metric.numpy_fallback"] == 0
    assert counts["metric.drain_sync"] == 1  # the per-epoch get only
    assert np.isfinite(metric.get()[1])


# ---------------------------------------------------------------------------
# bf16 recipes


def test_bf16_recipes_train_finite():
    """The bf16 recipe nets must TRAIN without NaN/inf through the fused
    K-step window (low-precision trunk, f32 loss/update math) — the
    in-process mirror of the suite record's `train_outputs_finite`
    probe."""
    bs = 8
    for build, shape in ((models.mlp, (bs, 784)),
                         (models.lenet, (bs, 1, 28, 28))):
        net = build(num_classes=10, dtype="bfloat16")
        mod = mx.mod.Module(net, context=mx.cpu())
        mod.bind(data_shapes=[mx.io.DataDesc("data", shape, "bfloat16")],
                 label_shapes=[mx.io.DataDesc("softmax_label", (bs,))])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(0)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rng.rand(*shape).astype(np.float32),
                              dtype="bfloat16")],
            label=[mx.nd.array(
                rng.randint(0, 10, (bs,)).astype(np.float32))])
        boundary = mod.train_window(batch, 2, publish_grads=False)
        boundary.wait()
        for out in boundary.outputs:
            arr = np.asarray(out._data, dtype=np.float32)
            assert np.all(np.isfinite(arr)), build.__name__


# ---------------------------------------------------------------------------
# FLOPs estimator + zoo registry


def test_flops_estimator_closed_forms():
    from mxnet_tpu.models import recipe

    # dense closed form: the MLP is exactly its three FC weight products
    mlp_sym = models.mlp(num_classes=10)
    expected = 784 * 128 + 128 * 64 + 64 * 10
    assert recipe.estimate_flops(mlp_sym, data=(4, 784)) == pytest.approx(
        expected, rel=1e-6)

    # MAC convention anchor: ResNet-50 @224 is the published ~4.1 GFLOPs
    resnet50 = models.resnet(num_classes=1000, num_layers=50,
                             image_shape="3,224,224")
    g = recipe.estimate_flops(resnet50, data=(1, 3, 224, 224))
    assert 3.8e9 < g < 4.3e9, g

    # VGG-16 @224 (~15.3e9) must land above ResNet-50 — conv cost scales
    vgg16 = models.vgg(num_classes=1000, num_layers=16)
    v = recipe.estimate_flops(vgg16, data=(1, 3, 224, 224))
    assert 14e9 < v < 17e9, v

    # estimate is per SAMPLE: batch size must not change it
    g8 = recipe.estimate_flops(resnet50, data=(8, 3, 224, 224))
    assert g8 == pytest.approx(g, rel=1e-3)


def test_flops_estimator_grouped_depthwise():
    from mxnet_tpu.models import recipe

    # grouped closed form: out_positions x num_filter x (in_ch/g) x kh x kw
    data = mx.sym.Variable("data")
    g4 = mx.sym.Convolution(data, num_filter=32, kernel=(3, 3), pad=(1, 1),
                            num_group=4, no_bias=True, name="g4")
    assert recipe.estimate_flops(g4, data=(1, 16, 8, 8)) == pytest.approx(
        8 * 8 * 32 * (16 // 4) * 3 * 3, rel=1e-6)

    # depthwise (num_group == channels): one input channel per filter
    dw = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3), pad=(1, 1),
                            num_group=16, no_bias=True, name="dw")
    assert recipe.estimate_flops(dw, data=(1, 16, 8, 8)) == pytest.approx(
        8 * 8 * 16 * 1 * 3 * 3, rel=1e-6)

    # ResNeXt-50 32x4d @224: the published ~4.23 GFLOPs. An estimator
    # that ignores num_group overcounts the grouped bottlenecks ~8x
    rx = models.resnext(num_classes=1000, num_layers=50,
                        image_shape="3,224,224")
    g = recipe.estimate_flops(rx, data=(1, 3, 224, 224))
    assert g == pytest.approx(4.2305e9, rel=0.02), g


def test_zoo_registry_covers_published_table():
    assert len(models.SCORE_SYMBOLS) >= 14
    for net in models.SCORE_SYMBOLS:
        sym = models.zoo.get_symbol(net)
        assert sym.list_arguments(), net
    with pytest.raises(ValueError):
        models.zoo.get_symbol("not-a-net")
