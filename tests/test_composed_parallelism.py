"""Composed 3D parallelism over one GraftMesh (ROADMAP item 3).

dp×pp and dp×tp×pp train steps as ONE program: GPipe stages on pp rank
sets, batch sharded over the dp sub-axis inside every microbatch, packed
per-stage parameter rows sharded over each stage's dp(×tp) rank set, and
gradients reduced over dp *within* the rank set. The oracle is serial
equivalence — outputs, gradients and post-update parameters must match the
identical chain trained as one plain single-device Module — plus the
placement contract (each device holds ~total/(S·dp·tp) packed bytes) and
the unchanged-fast-path contracts (fused K-step window, AOT cache, zero
per-window host syncs) on a composed mesh.
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu import telemetry as tm
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.mesh import GraftMesh, parse_mesh_spec, _reset_env_mesh
from mxnet_tpu.test_utils import assert_almost_equal

BATCH, DIM, HID, NCLS = 16, 8, 12, 5


# --------------------------------------------------------------------------
# mesh spec / GraftMesh construction
# --------------------------------------------------------------------------

def test_parse_mesh_spec():
    assert parse_mesh_spec("dp2,pp4") == {"dp": 2, "pp": 4}
    assert parse_mesh_spec("pp4,dp2") == {"dp": 2, "pp": 4}  # canonical order
    assert parse_mesh_spec("dp2xtp2xpp2") == {"dp": 2, "tp": 2, "pp": 2}
    assert parse_mesh_spec("auto", devices=list(range(8))) == {"dp": 8}
    assert parse_mesh_spec("dp*,pp4", devices=list(range(8))) == \
        {"dp": 2, "pp": 4}
    assert parse_mesh_spec("tp2,dp", devices=list(range(8))) == \
        {"dp": 4, "tp": 2}
    with pytest.raises(MXNetError):
        parse_mesh_spec("zz4")
    with pytest.raises(MXNetError):
        parse_mesh_spec("dp2,dp4")
    with pytest.raises(MXNetError):
        parse_mesh_spec("dp*,pp*", devices=list(range(8)))
    with pytest.raises(MXNetError):
        parse_mesh_spec("")
    with pytest.raises(MXNetError, match="strand"):
        # a wildcard must absorb EVERY remaining device, not floor-divide
        parse_mesh_spec("pp3,dp*", devices=list(range(8)))
    with pytest.raises(MXNetError, match="bad size"):
        parse_mesh_spec("dp2*,pp4")  # malformed size token, typed error


def test_graft_mesh_axes_and_shardings():
    gm = GraftMesh.from_spec("dp2,pp4")
    assert gm.spec == "dp2,pp4"
    assert gm.dp == 2 and gm.pp == 4 and gm.tp == 1 and gm.sp == 1
    assert gm.has("dp") and not gm.has("tp")
    assert str(gm.batch_sharding().spec) == "PartitionSpec('dp',)"
    assert str(gm.replicated().spec) == "PartitionSpec()"
    # wrapping is cache-transparent: same mesh -> equal + same hash
    assert parallel.as_graft(gm.mesh) == gm
    assert hash(parallel.as_graft(gm.mesh)) == hash(gm)
    # cache token is a process-stable rendering
    tok = gm.cache_token()
    assert tok[0] == "dp2,pp4" and len(tok[1]) == 8


# --------------------------------------------------------------------------
# module graph builders (heterogeneous chain; loss head on the last stage)
# --------------------------------------------------------------------------

def _stage_syms(n_mid):
    syms = []
    for i in range(n_mid):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=HID, name=f"st{i}_fc")
        syms.append(mx.sym.Activation(fc, act_type="tanh",
                                      name=f"st{i}_act"))
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=NCLS, name="st_last_fc")
    syms.append(mx.sym.SoftmaxOutput(fc, name="softmax"))
    return syms


def _chain_sym(n_mid):
    h = mx.sym.Variable("data")
    for i in range(n_mid):
        h = mx.sym.FullyConnected(h, num_hidden=HID, name=f"st{i}_fc")
        h = mx.sym.Activation(h, act_type="tanh", name=f"st{i}_act")
    h = mx.sym.FullyConnected(h, num_hidden=NCLS, name="st_last_fc")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _build_seq(mesh, n_mid):
    syms = _stage_syms(n_mid)
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms[:-1]):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    seq.add(mx.mod.Module(syms[-1], data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    return seq


def _oracle_for(seq, n_mid):
    ref = mx.mod.Module(_chain_sym(n_mid), context=mx.cpu())
    ref.bind(data_shapes=[("data", (BATCH, DIM))],
             label_shapes=[("softmax_label", (BATCH,))])
    args, auxs = seq.get_params()
    ref.init_params(arg_params={k: v.copy() for k, v in args.items()},
                    aux_params={k: v.copy() for k, v in auxs.items()},
                    initializer=None)
    return ref


def _batch(rs):
    data = mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))
    label = mx.nd.array(rs.randint(0, NCLS, (BATCH,)).astype(np.float32))
    return mx.io.DataBatch(data=[data], label=[label])


def _assert_parity(seq, ref, rs, steps=2):
    """Train both for `steps` SGD steps; outputs, gradients and params
    must match the single-device serial oracle."""
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    ref.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    for _ in range(steps):
        batch = _batch(rs)
        seq.forward(batch, is_train=True)
        seq.backward()
        ref.forward(batch, is_train=True)
        ref.backward()
        assert_almost_equal(seq.get_outputs()[0].asnumpy(),
                            ref.get_outputs()[0].asnumpy(),
                            rtol=1e-5, atol=1e-6)
        ref_grads = {n: g.asnumpy() for n, g in
                     ref._exec_group._exec.grad_dict.items()
                     if g is not None}
        for info in seq._pp_engine.infos:
            for (u, n) in info.param_entries:
                g = info.units[u].exec_.grad_dict[n].asnumpy()
                assert_almost_equal(g, ref_grads[n], rtol=1e-4, atol=1e-5,
                                    names=(f"pp:{n}", f"serial:{n}"))
        seq.update()
        ref.update()
    a_pp, _ = seq.get_params()
    a_ref, _ = ref.get_params()
    for n in a_ref:
        assert_almost_equal(a_pp[n].asnumpy(), a_ref[n].asnumpy(),
                            rtol=1e-4, atol=1e-5, names=(n, n))


# --------------------------------------------------------------------------
# composed train-step parity
# --------------------------------------------------------------------------

def test_dp_pp_train_step_matches_serial_oracle():
    rs = np.random.RandomState(7)
    gm = GraftMesh.from_spec("dp2,pp4")
    seq = _build_seq(gm, n_mid=3)
    eng = seq._pp_engine
    assert eng is not None and eng.S == 4 and eng.dp_size == 2
    assert not eng.homogeneous
    dp_reduce0 = tm.counter("parallel.dp_reduce").value
    _assert_parity(seq, _oracle_for(seq, 3), rs)
    # the composed program carried the gradient reduction over the dp
    # sub-axis within each stage's rank set (counter per ISSUE: "asserted
    # via HLO or counter"; the grad parity above is the numeric evidence —
    # a missing dp-sum would halve every gradient)
    assert tm.counter("parallel.dp_reduce").value > dp_reduce0


def test_dp_tp_pp_train_step_matches_serial_oracle():
    rs = np.random.RandomState(11)
    gm = GraftMesh.from_spec("dp2,tp2,pp2")
    seq = _build_seq(gm, n_mid=1)
    eng = seq._pp_engine
    assert eng is not None and eng.S == 2
    assert eng.dp_size == 2 and eng.tp_size == 2
    _assert_parity(seq, _oracle_for(seq, 1), rs)


def test_homogeneous_dp_pp_matches_serial():
    """Stacked (homogeneous) lowering under a dp sub-axis: grads psum over
    dp explicitly; parity against the serial chain."""
    rs = np.random.RandomState(3)
    gm = GraftMesh.from_spec("dp2,pp4")
    syms = []
    for i in range(4):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=DIM, name=f"blk{i}_fc")
        syms.append(mx.sym.Activation(fc, act_type="tanh",
                                      name=f"blk{i}_act"))
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    with parallel.with_mesh(gm):
        seq.bind(data_shapes=[("data", (BATCH, DIM))], for_training=False)
    seq.init_params(initializer=mx.init.Uniform(0.5))
    assert seq._pp_engine is not None and seq._pp_engine.homogeneous
    assert seq._pp_engine.dp_size == 2

    h = mx.sym.Variable("data")
    for i in range(4):
        h = mx.sym.FullyConnected(h, num_hidden=DIM, name=f"blk{i}_fc")
        h = mx.sym.Activation(h, act_type="tanh", name=f"blk{i}_act")
    ref = mx.mod.Module(h, context=mx.cpu(), label_names=None)
    ref.bind(data_shapes=[("data", (BATCH, DIM))], for_training=False)
    args, _ = seq.get_params()
    ref.init_params(arg_params={k: v.copy() for k, v in args.items()},
                    aux_params=None, initializer=None)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))],
        label=None)
    seq.forward(batch, is_train=False)
    ref.forward(batch, is_train=False)
    assert_almost_equal(seq.get_outputs()[0].asnumpy(),
                        ref.get_outputs()[0].asnumpy(),
                        rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("spec", ["dp2,pp2", "dp2,tp2,pp2"])
def test_dp_pp_batchnorm_aux_matches_group_granular_serial(spec):
    """BN under dp×pp (and dp×tp×pp): each (microbatch tick × dp shard)
    group normalizes by its own batch statistics, and the masked per-tick
    aux updates are averaged over ticks AND pmean-ed over the stage's
    rank set (identical tp contributions divide out). The oracle runs
    each group through the serial chain from the step-start aux and
    averages the EMA updates — the dp-extension of the pure-pp
    group-granular semantics the seed pins (and the reference's own
    non-sync multi-device BN behavior)."""
    rs = np.random.RandomState(5)
    gm = GraftMesh.from_spec(spec)
    d0 = mx.sym.Variable("data")
    fc0 = mx.sym.FullyConnected(d0, num_hidden=HID, name="b0_fc")
    bn0 = mx.sym.BatchNorm(fc0, name="b0_bn", fix_gamma=False,
                           momentum=0.9)
    s0 = mx.sym.Activation(bn0, act_type="tanh", name="b0_act")
    d1 = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(d1, num_hidden=NCLS, name="b1_fc")
    s1 = mx.sym.SoftmaxOutput(fc1, name="softmax")
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(s0, data_names=("data",), label_names=None))
    seq.add(mx.mod.Module(s1, data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with parallel.with_mesh(gm):
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    seq.init_params(initializer=mx.init.Uniform(0.5))

    h = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(h, num_hidden=HID, name="b0_fc")
    h = mx.sym.BatchNorm(h, name="b0_bn", fix_gamma=False, momentum=0.9)
    h = mx.sym.Activation(h, act_type="tanh", name="b0_act")
    h = mx.sym.FullyConnected(h, num_hidden=NCLS, name="b1_fc")
    h = mx.sym.SoftmaxOutput(h, name="softmax")
    ref = mx.mod.Module(h, context=mx.cpu())
    M, dp = seq._pp_engine.M, seq._pp_engine.dp_size
    grp = BATCH // (M * dp)
    ref.bind(data_shapes=[("data", (grp, DIM))],
             label_shapes=[("softmax_label", (grp,))])
    args, auxs = seq.get_params()
    args = {k: v.copy() for k, v in args.items()}
    auxs = {k: v.copy() for k, v in auxs.items()}

    xs = rs.randn(BATCH, DIM).astype(np.float32)
    ys = rs.randint(0, NCLS, (BATCH,)).astype(np.float32)
    seq.forward(mx.io.DataBatch(data=[mx.nd.array(xs)],
                                label=[mx.nd.array(ys)]), is_train=True)
    out_pp = seq.get_outputs()[0].asnumpy()
    _, aux_pp = seq.get_params()

    # oracle over the M·dp independent normalization groups: microbatch m
    # spans rows [m·(B/M), (m+1)·(B/M)); the dp shard r takes its r-th
    # contiguous slice of that microbatch
    mean_sum = None
    var_sum = None
    for m in range(M):
        for r in range(dp):
            lo = m * (BATCH // M) + r * grp
            rows = slice(lo, lo + grp)
            ref.set_params({k: v.copy() for k, v in args.items()},
                           {k: v.copy() for k, v in auxs.items()})
            ref.forward(mx.io.DataBatch(
                data=[mx.nd.array(xs[rows])],
                label=[mx.nd.array(ys[rows])]), is_train=True)
            assert_almost_equal(ref.get_outputs()[0].asnumpy(),
                                out_pp[rows], rtol=1e-4, atol=1e-5,
                                names=(f"serial[{m},{r}]", "pp"))
            # read aux straight off the oracle's executor (get_params
            # would return the set_params snapshot)
            aux_exec = ref._exec_group._exec.aux_dict
            mm = aux_exec["b0_bn_moving_mean"].asnumpy().copy()
            mv = aux_exec["b0_bn_moving_var"].asnumpy().copy()
            mean_sum = mm if mean_sum is None else mean_sum + mm
            var_sum = mv if var_sum is None else var_sum + mv
    n = M * dp
    assert_almost_equal(aux_pp["b0_bn_moving_mean"].asnumpy(),
                        mean_sum / n, rtol=1e-4, atol=1e-6)
    assert_almost_equal(aux_pp["b0_bn_moving_var"].asnumpy(),
                        var_sum / n, rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------------
# per-stage per-device placement
# --------------------------------------------------------------------------

@pytest.mark.parametrize("spec,shard", [("dp2,pp4", 2), ("dp2,tp2,pp2", 4)])
def test_packed_rows_hold_one_rank_set_slice_per_device(spec, shard):
    """Each device holds ~total/(S·dp·tp) packed parameter bytes: row i of
    the (S, Lmax) buffer lives on pp rank set i, split over its dp(×tp)
    sub-mesh."""
    gm = GraftMesh.from_spec(spec)
    seq = _build_seq(gm, n_mid=3 if gm.pp == 4 else 1)
    eng = seq._pp_engine
    eng.retain_packed = True
    rs = np.random.RandomState(0)
    seq.forward(_batch(rs), is_train=True)
    assert eng._packed_params, "composed mode must pack rows"
    S = eng.S
    for dt, buf in eng._packed_params.items():
        total = buf.size * buf.dtype.itemsize
        per_dev = total // (S * shard)
        shapes = {s.data.shape for s in buf.addressable_shards}
        assert shapes == {(buf.shape[0] // S, buf.shape[1] // shard)}, (
            f"{dt}: shards {shapes}, want row/(dp·tp) slices")
        for s in buf.addressable_shards:
            got = s.data.size * buf.dtype.itemsize
            assert got == per_dev, f"{dt}: device holds {got}B != {per_dev}B"
    # the placement gauge reports the same number
    gauge = tm.gauge("parallel.packed_bytes_per_device").value
    assert gauge > 0


# --------------------------------------------------------------------------
# fused window / AOT / no-host-sync invariants on a composed mesh
# --------------------------------------------------------------------------

def _plain_module_on(gm):
    sym = _chain_sym(1)
    mod = mx.mod.Module(sym, context=mx.cpu())
    with parallel.with_mesh(gm):
        mod.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
        mod.init_params(initializer=mx.init.Uniform(0.5))
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05})
    return mod


def test_fused_window_invariants_on_composed_mesh():
    """The K-step fused train window runs unchanged over a dp×pp mesh: one
    compile, then zero XLA compiles AND zero host syncs per window
    (counter-verified), with the dp batch sharding intact."""
    rs = np.random.RandomState(9)
    gm = GraftMesh.from_spec("dp2,pp4")
    mod = _plain_module_on(gm)
    exe = mod._exec_group._exec
    assert str(exe.arg_dict["data"]._data.sharding.spec) == \
        "PartitionSpec('dp',)"

    def window(n=2):
        with parallel.with_mesh(gm):
            mod.train_window(_batch(rs), n_steps=n)
            mod.get_outputs()[0].wait_to_read()

    window()  # compile
    compiles0 = tm.counter("executor.jit_compile").value
    sync0 = (tm.counter("ndarray.asnumpy").value,
             tm.counter("ndarray.wait_to_read").value)
    window()
    window()
    assert tm.counter("executor.jit_compile").value == compiles0, \
        "steady-state composed windows must not recompile"
    sync1 = (tm.counter("ndarray.asnumpy").value,
             tm.counter("ndarray.wait_to_read").value)
    # the two wait_to_read fences above are the caller's own sync points;
    # the window dispatch itself must add no host syncs
    assert sync1[0] == sync0[0], "composed window forced an asnumpy sync"
    assert sync1[1] - sync0[1] <= 2, \
        f"composed window added host syncs: {sync1[1] - sync0[1]}"


@pytest.mark.aot_serialization
def test_aot_cache_hit_on_composed_mesh(tmp_path, monkeypatch):
    """Mesh-sharded programs persist to the AOT executable cache keyed by
    the GraftMesh spec + device assignment: a second bind of the same
    graph on the same composed mesh loads the executable (cache_hit) and
    performs zero XLA compiles."""
    monkeypatch.setenv("MXNET_AOT_CACHE", "1")
    monkeypatch.setenv("MXNET_AOT_CACHE_DIR", str(tmp_path))
    rs = np.random.RandomState(4)
    gm = GraftMesh.from_spec("dp2,pp4")

    mod_a = _plain_module_on(gm)
    with parallel.with_mesh(gm):
        mod_a.train_window(_batch(rs), n_steps=2)
        mod_a.get_outputs()[0].wait_to_read()
    stored = tm.counter("aot.cache_store").value
    assert stored > 0, "composed-mesh program did not persist"

    hits0 = tm.counter("aot.cache_hit").value
    compiles0 = tm.counter("executor.jit_compile").value
    mod_b = _plain_module_on(gm)
    with parallel.with_mesh(gm):
        mod_b.train_window(_batch(rs), n_steps=2)
        mod_b.get_outputs()[0].wait_to_read()
    assert tm.counter("aot.cache_hit").value > hits0, \
        "second composed-mesh bind missed the executable cache"
    assert tm.counter("executor.jit_compile").value == compiles0, \
        "second composed-mesh bind recompiled"


# --------------------------------------------------------------------------
# MXNET_MESH environment construction
# --------------------------------------------------------------------------

def test_mesh_from_env_binds_executor_group(monkeypatch):
    monkeypatch.setenv("MXNET_MESH", "dp8")
    _reset_env_mesh()
    try:
        mod = mx.mod.Module(_chain_sym(1), context=mx.cpu())
        mod.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
        mod.init_params(initializer=mx.init.Uniform(0.5))
        exe = mod._exec_group._exec
        assert str(exe.arg_dict["data"]._data.sharding.spec) == \
            "PartitionSpec('dp',)"
        assert mod._exec_group._dp_size == 8
        mod.forward(_batch(np.random.RandomState(0)), is_train=False)
        mod.get_outputs()[0].wait_to_read()
    finally:
        _reset_env_mesh()


def test_mesh_from_env_lowers_sequential_module(monkeypatch):
    monkeypatch.setenv("MXNET_MESH", "dp2,pp4")
    _reset_env_mesh()
    try:
        syms = _stage_syms(3)
        seq = mx.mod.SequentialModule()
        for i, s in enumerate(syms[:-1]):
            seq.add(mx.mod.Module(s, data_names=("data",),
                                  label_names=None), auto_wiring=i > 0)
        seq.add(mx.mod.Module(syms[-1], data_names=("data",),
                              label_names=("softmax_label",)),
                take_labels=True, auto_wiring=True)
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
        assert seq._pp_engine is not None
        assert seq._pp_engine.S == 4 and seq._pp_engine.dp_size == 2
    finally:
        _reset_env_mesh()


def test_installed_mesh_wins_over_env(monkeypatch):
    monkeypatch.setenv("MXNET_MESH", "dp8")
    _reset_env_mesh()
    try:
        gm = GraftMesh.from_spec("dp2,pp4")
        with parallel.with_mesh(gm):
            assert parallel.current_graft() == gm
    finally:
        _reset_env_mesh()


def test_microbatch_not_divisible_by_dp_raises():
    gm = GraftMesh.from_spec("dp2,pp4")
    syms = _stage_syms(3)
    seq = mx.mod.SequentialModule(pipeline_microbatches=8)
    for i, s in enumerate(syms[:-1]):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    seq.add(mx.mod.Module(syms[-1], data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with pytest.raises(MXNetError, match="data-parallel degree"):
        with parallel.with_mesh(gm):
            # 16/8 = 2-row microbatches cannot split over dp=2... they can;
            # use a batch that breaks: 8 microbatches of 1 row each
            seq.bind(data_shapes=[("data", (8, DIM))],
                     label_shapes=[("softmax_label", (8,))])


# --------------------------------------------------------------------------
# composed-mesh kill-and-resume (elastic v2 checkpoints under dp×pp)
# --------------------------------------------------------------------------

def _run_elastic_worker(env, timeout=240):
    import subprocess
    import sys
    root = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    e = dict(os.environ)
    clean = [p for p in e.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    e["PYTHONPATH"] = os.pathsep.join([root] + clean)
    e["JAX_PLATFORMS"] = "cpu"
    e.pop("XLA_FLAGS", None)  # worker sets its own 8-device flag
    e.update(env)
    return subprocess.run(
        [sys.executable, os.path.join(root, "tests",
                                      "ckpt_elastic_worker.py")],
        capture_output=True, text=True, env=e, timeout=timeout, cwd=root,
    )


@pytest.mark.chaos
def test_kill_resume_composed_mesh_matches_single_host_pin(tmp_path):
    """Hard-kill mid-epoch while training a 2-stage pipeline under
    dp2,pp2 with sharded v2 checkpoints; the restarted worker must
    auto-resume from the last commit and reach the SAME convergence pin
    as the single-host kill-resume test (final_update=48, acc > 0.8)."""
    d = str(tmp_path / "ckpts")
    base = {
        "MXNET_CHECKPOINT_DIR": d,
        "MXNET_CHECKPOINT_BATCH_PERIOD": "3",
        "WORKER_MESH": "dp2,pp2",
    }
    r1 = _run_elastic_worker({**base, "MXNET_FI_CRASH_AT_BATCH": "20"})
    assert r1.returncode == 17, (r1.stdout + r1.stderr)[-3000:]

    from mxnet_tpu import checkpoint as ckpt
    pre = ckpt.load_latest(d)
    assert pre is not None
    assert (pre.next_epoch, pre.next_batch) == (2, 3)
    m = pre.manifest
    assert m["format"] == 2 and m["mesh"]["spec"] == "dp2,pp2"

    r2 = _run_elastic_worker({**base, "MXNET_FI_CRASH_AT_BATCH": "20",
                              "MXNET_NUM_RESTARTS": "1"})
    out = r2.stdout + r2.stderr
    assert r2.returncode == 0, out[-3000:]
    assert "RESUME epoch=2 batch=3 num_update=19" in out, out[-3000:]
    done = [l for l in out.splitlines() if l.startswith("TRAIN-DONE")]
    assert done, out[-3000:]
    assert int(done[0].split("final_update=")[1]) == 48
    assert float(done[0].split("acc=")[1].split()[0]) > 0.8
