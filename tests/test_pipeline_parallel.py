"""Pipeline parallelism: GPipe microbatch schedule over a pp mesh axis.

Beyond-reference surface (SURVEY.md §2.5 marks scheduled pipelining absent
there); the oracle is serial equivalence — the pipelined program must equal
running the stage stack sequentially, for outputs AND gradients.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.test_utils import assert_almost_equal

import jax
import jax.numpy as jnp


def _stage(params, a):
    w, b = params
    return jnp.tanh(a @ w + b)


def _serial(stage_params, x):
    # x: (M, mb, d); apply stages sequentially
    S = stage_params[0].shape[0]
    y = x
    for s in range(S):
        y = _stage((stage_params[0][s], stage_params[1][s]), y)
    return y


@pytest.mark.parametrize("S,M", [(4, 8), (2, 2)])
def test_pipeline_matches_serial_forward_and_grad(S, M):
    mesh = parallel.make_mesh({"pp": S})
    d, mb = 16, 4
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(S, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(S, d).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))

    out = parallel.pipeline_apply(_stage, (ws, bs), x, mesh)
    ref = _serial((ws, bs), x)
    assert_almost_equal(np.asarray(out), np.asarray(ref),
                        rtol=1e-5, atol=1e-6)

    def loss_pp(ws, bs):
        return jnp.sum(parallel.pipeline_apply(_stage, (ws, bs), x, mesh) ** 2)

    def loss_serial(ws, bs):
        return jnp.sum(_serial((ws, bs), x) ** 2)

    g_pp = jax.grad(loss_pp, argnums=(0, 1))(ws, bs)
    g_ref = jax.grad(loss_serial, argnums=(0, 1))(ws, bs)
    for a, b in zip(g_pp, g_ref):
        assert_almost_equal(np.asarray(a), np.asarray(b),
                            rtol=1e-4, atol=1e-5)


def test_pipeline_jits_and_trains():
    """One jitted train step over the pipeline: params move, loss falls."""
    S, M, d, mb = 4, 4, 8, 8
    mesh = parallel.make_mesh({"pp": S})
    rng = np.random.RandomState(1)
    ws = jnp.asarray(rng.randn(S, d, d).astype(np.float32) * 0.3)
    bs = jnp.zeros((S, d), jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))
    tgt = jnp.asarray(rng.randn(M, mb, d).astype(np.float32))

    @jax.jit
    def step(ws, bs):
        def loss(ws, bs):
            y = parallel.pipeline_apply(_stage, (ws, bs), x, mesh)
            return jnp.mean((y - tgt) ** 2)

        l, g = jax.value_and_grad(loss, argnums=(0, 1))(ws, bs)
        return l, ws - 0.1 * g[0], bs - 0.1 * g[1]

    losses = []
    for _ in range(20):
        l, ws, bs = step(ws, bs)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, losses[:3] + losses[-3:]


def test_microbatch_helpers():
    x = jnp.arange(24.0).reshape(12, 2)
    m = parallel.microbatch(x, 4)
    assert m.shape == (4, 3, 2)
    with pytest.raises(mx.base.MXNetError):
        parallel.microbatch(x, 5)
    stages = [(jnp.ones((2, 2)), jnp.zeros(2)) for _ in range(3)]
    stacked = parallel.stack_stage_params(stages)
    assert stacked[0].shape == (3, 2, 2) and stacked[1].shape == (3, 2)
