"""Unit tests for the pluggable kvstore transport layer.

Fast, in-process, CPU-only: the wire-protocol hardening (crc32 trailer,
bf16/int8 dtype codes), the reconnect/backoff client machinery (a socket
that dies mid-frame must be retried, a gone server must become a TYPED
error), the CollectiveTransport seam under DistKVStore, and the elastic
coordinator's round/membership state machine driven by real sockets and
threads. Subprocess chaos legs live in tests/test_elastic_train.py
(slow-marked).
"""

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import kvstore_async as ka
from mxnet_tpu import kvstore_elastic as ke
from mxnet_tpu import kvstore_transport as kt
from mxnet_tpu import telemetry as tm
from mxnet_tpu.base import MXNetError

# the elastic coordinator + clients run as real threads in-process: tier-1
# runs this whole file under the runtime lock-order sanitizer
pytestmark = pytest.mark.sanitize


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _counter(name):
    group, _, leaf = name.partition(".")
    return tm.snapshot().get(group, {}).get(leaf, 0)


# ---------------------------------------------------------------------------
# wire protocol: crc32 trailer + new dtype codes


def test_crc_frame_roundtrip_and_corruption_detected():
    a, b = socket.socketpair()
    try:
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        frame = ka._pack_frame(ka._OP_PUSH, "w0", arr, crc=True)
        a.sendall(frame)
        op, flags, k, got = ka._recv_frame(b)
        assert op == ka._OP_PUSH and k == "w0"
        assert flags & ka._FLAG_CRC
        np.testing.assert_array_equal(got, arr)

        # flip one payload byte: the crc32 trailer must catch it
        bad = bytearray(frame)
        bad[len(bad) // 2] ^= 0xFF
        a.sendall(bytes(bad))
        with pytest.raises(ka._WireError):
            ka._recv_frame(b)
    finally:
        a.close()
        b.close()


def test_crc_with_hmac_covers_trailer():
    a, b = socket.socketpair()
    key = b"k" * 32
    try:
        arr = np.ones(5, dtype=np.float32)
        frame = ka._pack_frame(ka._OP_PUSH, "w0", arr, secret=key, crc=True)
        a.sendall(frame)
        op, _, _, got = ka._recv_frame(b, secret=key)
        assert op == ka._OP_PUSH
        np.testing.assert_array_equal(got, arr)

        # corrupt the crc trailer itself: the MAC is computed over it,
        # so tampering there is also unauthenticated
        bad = bytearray(frame)
        bad[-36] ^= 0x01  # inside the 4-byte crc, before the 32-byte mac
        a.sendall(bytes(bad))
        with pytest.raises(ka._WireError):
            ka._recv_frame(b, secret=key)
    finally:
        a.close()
        b.close()


def test_int8_and_bf16_dtype_codes_roundtrip():
    a, b = socket.socketpair()
    try:
        q = np.array([-127, 0, 42, 127], dtype=np.int8)
        a.sendall(ka._pack_frame(ka._OP_PUSH, "g", q, crc=True))
        _, _, _, got = ka._recv_frame(b)
        assert got.dtype == np.int8
        np.testing.assert_array_equal(got, q)

        try:
            import ml_dtypes
        except ImportError:
            pytest.skip("ml_dtypes unavailable")
        h = np.arange(4, dtype=np.float32).astype(ml_dtypes.bfloat16)
        a.sendall(ka._pack_frame(ka._OP_PUSH, "h", h, crc=True))
        _, _, _, got = ka._recv_frame(b)
        assert got.dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(got.astype(np.float32),
                                      h.astype(np.float32))
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# reconnect machinery


def test_backoff_delay_is_jittered_and_capped():
    for attempt in range(1, 12):
        for _ in range(20):
            d = kt.backoff_delay(attempt, base=0.05, cap=1.0)
            assert 0 <= d <= min(1.0, 0.05 * 2 ** (attempt - 1))


def test_connect_with_backoff_raises_typed_error():
    port = _free_port()  # nothing listens here
    t0 = time.time()
    with pytest.raises(kt.PeerUnreachable) as ei:
        kt.connect_with_backoff(("127.0.0.1", port), deadline_s=0.4,
                                what="unit test peer")
    assert time.time() - t0 < 30
    assert "MXNET_KV_RECONNECT" in str(ei.value)


def test_async_rpc_survives_socket_death_mid_frame(monkeypatch):
    """Satellite: the dist_async client must reconnect (backoff+jitter)
    when the server connection dies mid-frame, and the retried RPC must
    succeed against the recovered server."""
    port = _free_port()
    lis = socket.socket()
    lis.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lis.bind(("127.0.0.1", port))
    lis.listen(4)

    def server():
        # first connection: read a bit, answer with HALF a frame, die
        conn, _ = lis.accept()
        conn.recv(64)
        conn.sendall(ka._HDR.pack(b"MXPS", 1, ka._OP_OK, 0, 0, 0, 0, 0)[:9])
        conn.close()
        # second connection: speak the real protocol
        conn, _ = lis.accept()
        op, flags, key, arr = ka._recv_frame(conn)
        assert op == ka._OP_PUSH and key == "w0"
        conn.sendall(ka._pack_frame(ka._OP_OK))
        conn.close()

    t = threading.Thread(target=server, daemon=True)
    t.start()
    monkeypatch.setenv("MXNET_PROC_ID", "1")
    monkeypatch.setenv("MXNET_NUM_PROCS", "2")
    monkeypatch.setenv("MXNET_PS_PORT", str(port))
    monkeypatch.delenv("MXNET_PS_KEY", raising=False)
    kv = ka.AsyncDistKVStore.__new__(ka.AsyncDistKVStore)
    from mxnet_tpu.kvstore import KVStore

    KVStore.__init__(kv, "dist_async")
    kv._rank, kv._size = 1, 2
    kv._server = None
    kv._addr = ("127.0.0.1", port)
    kv._sock = None
    kv._sock_lock = threading.Lock()
    kv._has_optimizer = False
    before = _counter("kvstore_async.reconnect")
    kv._rpc(ka._OP_PUSH, "w0", np.zeros(3, np.float32))
    assert _counter("kvstore_async.reconnect") > before
    t.join(5)
    lis.close()


def test_async_rpc_gone_server_is_typed_not_hang(monkeypatch):
    monkeypatch.setenv("MXNET_KV_RECONNECT", "0.5")
    monkeypatch.delenv("MXNET_PS_KEY", raising=False)
    kv = ka.AsyncDistKVStore.__new__(ka.AsyncDistKVStore)
    from mxnet_tpu.kvstore import KVStore

    KVStore.__init__(kv, "dist_async")
    kv._rank, kv._size = 1, 2
    kv._server = None
    kv._addr = ("127.0.0.1", _free_port())
    kv._sock = None
    kv._sock_lock = threading.Lock()
    kv._has_optimizer = False
    t0 = time.time()
    with pytest.raises(kt.PeerUnreachable):
        kv._rpc(ka._OP_PUSH, "w0", np.zeros(3, np.float32))
    assert time.time() - t0 < 30


# ---------------------------------------------------------------------------
# the CollectiveTransport seam


class _FakeTransport(kt.CollectiveTransport):
    name = "fake"

    def __init__(self):
        self.calls = []

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 2

    def allreduce(self, value, key="", clock=0):
        self.calls.append("allreduce")
        return value._data

    def broadcast_ints(self, values):
        self.calls.append("broadcast")
        return [int(v) for v in values]

    def barrier(self):
        self.calls.append("barrier")


def test_dist_kvstore_routes_through_injected_transport(monkeypatch):
    from mxnet_tpu.kvstore import DistKVStore

    monkeypatch.setenv("MXNET_KV_TIMEOUT", "0")
    tr = _FakeTransport()
    kv = DistKVStore("dist_sync", transport=tr)
    assert kv.rank == 0 and kv.num_workers == 2
    assert kv.broadcast_ints([3, 4]) == [3, 4]
    kv.barrier()
    assert "broadcast" in tr.calls and "barrier" in tr.calls


def test_make_transport_unknown_kind_fails_loudly(monkeypatch):
    monkeypatch.setenv("MXNET_KV_TRANSPORT", "carrier-pigeon")
    with pytest.raises(MXNetError):
        kt.make_transport()


def test_mesh_transport_single_process_identities():
    tr = kt.MeshTransport()
    assert tr.num_workers == 1
    assert tr.broadcast_ints([5, 6]) == [5, 6]
    tr.barrier()  # no-op, must not raise
    assert tr.epoch() == 0


# ---------------------------------------------------------------------------
# elastic coordinator state machine (real sockets, fast timeouts)


def _pair(monkeypatch, **env):
    """One in-process coordinator + two clients on a fresh port."""
    monkeypatch.setenv("MXNET_KV_HEARTBEAT_MS", "100")
    monkeypatch.setenv("MXNET_KV_PEER_TIMEOUT", "2.0")
    monkeypatch.setenv("MXNET_KV_RECONNECT", "10")
    monkeypatch.setenv("MXNET_PS_EXIT_TIMEOUT", "5")
    monkeypatch.delenv("MXNET_PS_KEY", raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    addr = ("127.0.0.1", _free_port())
    kv0 = ke.ElasticDistKVStore(rank=0, num_workers=2, addr=addr,
                                run_server=True)
    kv1 = ke.ElasticDistKVStore(rank=1, num_workers=2, addr=addr,
                                run_server=False)
    return kv0, kv1


def _close(*kvs):
    # clients first, coordinator last: rank 0's close waits for everyone
    # else to LEAVE before tearing the server down
    for kv in reversed(kvs):
        try:
            kv.close()
        except MXNetError:
            pass


def test_elastic_round_reduces_and_replies_carry_epoch(monkeypatch):
    import mxnet_tpu as mx

    kv0, kv1 = _pair(monkeypatch)
    try:
        for kv in (kv0, kv1):
            kv.init(0, mx.nd.array(np.zeros(4, np.float32)))
        outs = {}

        def step(kv, tag):
            kv.push(0, mx.nd.array(np.full(4, kv.rank + 1.0, np.float32)))
            o = mx.nd.array(np.zeros(4, np.float32))
            kv.pull(0, out=o)
            outs[tag] = o.asnumpy()

        ts = [threading.Thread(target=step, args=(kv, t))
              for kv, t in ((kv0, "a"), (kv1, "b"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        # no updater installed: push-replace with the reduced sum (1+2)
        np.testing.assert_allclose(outs["a"], 3.0)
        np.testing.assert_allclose(outs["b"], 3.0)
        assert kv0._seen_epoch >= 2  # both joins observed on replies
    finally:
        _close(kv0, kv1)


def test_elastic_compression_error_feedback(monkeypatch):
    import mxnet_tpu as mx

    kv0, kv1 = _pair(monkeypatch, MXNET_KV_COMPRESS="int8")
    try:
        for kv in (kv0, kv1):
            kv.init(0, mx.nd.array(np.zeros(3, np.float32)))
        g = np.array([1.0, -0.004, 0.5], np.float32)
        before = _counter("kvstore.compress_push")

        def step(kv):
            kv.push(0, mx.nd.array(g))
            o = mx.nd.array(np.zeros(3, np.float32))
            kv.pull(0, out=o)

        ts = [threading.Thread(target=step, args=(kv,))
              for kv in (kv0, kv1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert _counter("kvstore.compress_push") >= before + 2
        # error feedback: the quantization residual of the tiny component
        # is remembered client-side for the next push
        res = kv0._residual.get("0")
        assert res is not None and res.shape == (3,)
        scale = max(abs(float(np.max(np.abs(g)))), 1e-30) / 127.0
        np.testing.assert_allclose(
            res, g - np.clip(np.rint(g / scale), -127, 127) * scale,
            atol=1e-7)
    finally:
        _close(kv0, kv1)


def test_elastic_backup_worker_drops_slowest(monkeypatch):
    import mxnet_tpu as mx

    kv0, kv1 = _pair(monkeypatch, MXNET_KV_BACKUP_WORKERS="1")
    try:
        for kv in (kv0, kv1):
            kv.init(0, mx.nd.array(np.zeros(2, np.float32)))
        # rank 0 alone closes the round (expected 2, need 2-1=1); the
        # aggregate is rescaled by expected/arrived = 2
        kv0.push(0, mx.nd.array(np.ones(2, np.float32)))
        o = mx.nd.array(np.zeros(2, np.float32))
        kv0.pull(0, out=o)
        np.testing.assert_allclose(o.asnumpy(), 2.0)
        before = _counter("kvstore.drop_slowest")
        # rank 1's late contribution to the closed round is discarded
        kv1.push(0, mx.nd.array(np.ones(2, np.float32)))
        assert _counter("kvstore.drop_slowest") > before
        # ...and its clock fast-forwards onto the live round line
        assert kv1._clock["0"] == kv0._clock["0"]
    finally:
        _close(kv0, kv1)


def test_elastic_corrupt_frame_rejected_not_absorbed(monkeypatch):
    import mxnet_tpu as mx

    kv0, kv1 = _pair(monkeypatch)
    try:
        for kv in (kv0, kv1):
            kv.init(0, mx.nd.array(np.ones(2, np.float32)))
        before = _counter("kvstore.corrupt_frame_rejected")
        # raw garbage straight at the coordinator: detected + refused
        s = socket.create_connection(kv0._addr, timeout=5)
        s.sendall(b"GET / HTTP/1.1\r\n\r\n" + b"\x00" * 64)
        s.settimeout(5)
        try:
            while s.recv(4096):
                pass
        except OSError:
            pass
        s.close()
        assert _counter("kvstore.corrupt_frame_rejected") > before
        # the store was not perturbed: a clean pull still works
        o = mx.nd.array(np.zeros(2, np.float32))
        kv1.pull(0, out=o)
        np.testing.assert_allclose(o.asnumpy(), 1.0)
    finally:
        _close(kv0, kv1)


def test_elastic_chaos_drop_and_corrupt_frames_retry_clean(monkeypatch):
    import mxnet_tpu as mx
    from mxnet_tpu import faultinject as _fi

    kv0, kv1 = _pair(monkeypatch)
    try:
        for kv in (kv0, kv1):
            kv.init(0, mx.nd.array(np.zeros(2, np.float32)))
        _fi.reset()
        monkeypatch.setenv("MXNET_FI_KV_DROP_EVERY", "3")
        monkeypatch.setenv("MXNET_FI_KV_CORRUPT_EVERY", "4")
        monkeypatch.setenv("MXNET_FI_ATTEMPT", "-1")
        outs = {}

        def steps(kv, tag):
            for c in range(4):
                kv.push(0, mx.nd.array(np.ones(2, np.float32)))
                o = mx.nd.array(np.zeros(2, np.float32))
                kv.pull(0, out=o)
                outs[tag] = o.asnumpy()

        ts = [threading.Thread(target=steps, args=(kv, t))
              for kv, t in ((kv0, "a"), (kv1, "b"))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert "a" in outs and "b" in outs, "chaos run hung"
        # every round still reduced exactly both contributions (push with
        # no updater replaces the store with the round's sum: 1 + 1)
        np.testing.assert_allclose(outs["a"], 2.0)
        np.testing.assert_allclose(outs["b"], 2.0)
        assert _counter("faultinject.kv_drop") > 0
        assert _counter("faultinject.kv_corrupt") > 0
        # the corrupted frames were DETECTED server-side, then resent clean
        assert _counter("kvstore.corrupt_frame_rejected") > 0
    finally:
        monkeypatch.delenv("MXNET_FI_KV_DROP_EVERY", raising=False)
        monkeypatch.delenv("MXNET_FI_KV_CORRUPT_EVERY", raising=False)
        _close(kv0, kv1)


def test_elastic_join_bumps_epoch_and_fence_agrees_cursor(monkeypatch):
    import mxnet_tpu as mx

    kv0, kv1 = _pair(monkeypatch)
    kv2 = None
    try:
        for kv in (kv0, kv1):
            kv.init(0, mx.nd.array(np.zeros(2, np.float32)))
        # wait for the startup churn (two joins) to reach both clients via
        # heartbeat replies, then baseline (set_optimizer's job in fit)
        deadline = time.time() + 5
        while time.time() < deadline and (
                kv0._seen_epoch < 2 or kv1._seen_epoch < 2):
            time.sleep(0.05)
        kv0._acked_epoch = kv0._seen_epoch
        kv1._acked_epoch = kv1._seen_epoch
        kv2 = ke.ElasticDistKVStore(rank=2, num_workers=3, addr=kv0._addr,
                                    run_server=False)
        # survivors observe the join on their next heartbeat reply
        ev = None
        deadline = time.time() + 5
        while time.time() < deadline:
            ev = kv0.membership_event()
            if ev is not None and ev.num_workers == 3:
                break
            time.sleep(0.05)
        assert ev is not None and ev.num_workers == 3
        res = {}
        ts = [threading.Thread(
            target=lambda kv=kv, c=c: res.update(
                {kv.rank: kv.reshard_barrier(*c)}))
            for kv, c in ((kv0, (5, 40)), (kv1, (5, 37)))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        # joiner was admitted AT the current epoch: fence completes
        # without it; consensus cursor is the min over survivors
        assert res[0] == res[1]
        epoch, nw, ce, cb = res[0]
        assert nw == 3 and (ce, cb) == (5, 37)
        assert kv0.num_workers == 3
        assert kv0.membership_event() is None
    finally:
        _close(*(kv for kv in (kv0, kv1, kv2) if kv is not None))


def test_elastic_rejected_error_paths_are_typed(monkeypatch):
    import mxnet_tpu as mx

    kv0, _kv1 = _pair(monkeypatch)
    try:
        # pushing a key that was never initialized: typed recovery signal
        with pytest.raises(kt.ElasticServerLost):
            kv0.push(99, mx.nd.array(np.zeros(2, np.float32)))
    finally:
        _close(kv0, _kv1)
