"""Multi-device tests on the 8-device virtual CPU mesh
(reference test_multi_device_exec.py, test_model_parallel.py, and the
distributed-semantics strategy of SURVEY.md §4: process-level fakes)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _toy(n=512, d=16, k=3, seed=42):
    r = np.random.RandomState(seed)
    W = r.randn(d, k)
    X = r.randn(n, d).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.float32)
    return X, Y


def _mlp(k=3):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=24, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=k, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_eight_device_data_parallel_converges():
    X, Y = _toy()
    train = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.fit(
        train, optimizer="sgd", optimizer_params={"learning_rate": 0.5},
        num_epoch=10, initializer=mx.init.Xavier(),
    )
    acc = mod.score(val, "acc")[0][1]
    assert acc > 0.9

    exe = mod._exec_group._exec
    # data sharded over dp, params replicated (XLA inserts the psum)
    assert str(exe.arg_dict["data"]._data.sharding.spec) == "PartitionSpec('dp',)"
    assert str(exe.arg_dict["fc1_weight"]._data.sharding.spec) == "PartitionSpec()"


def test_multi_device_matches_single_device():
    """DP over 8 devices must produce identical updates to 1 device
    (the reference's convergence-parity claim, BASELINE.md)."""
    X, Y = _toy(n=128)
    params = {}
    for ctxs in [[mx.cpu()], [mx.cpu(i) for i in range(8)]]:
        mx.random.seed(3)
        train = mx.io.NDArrayIter(X, Y, batch_size=32)
        mod = mx.mod.Module(_mlp(), context=ctxs)
        mod.fit(
            train, optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            num_epoch=2, initializer=mx.init.Uniform(0.05),
        )
        arg_params, _ = mod.get_params()
        params[len(ctxs)] = {k: v.asnumpy() for k, v in arg_params.items()}
    for k in params[1]:
        assert_almost_equal(
            params[1][k], params[8][k], rtol=1e-4, atol=1e-5,
            names=(f"1dev:{k}", f"8dev:{k}"),
        )


def test_mesh_helpers():
    import jax

    mesh = mx.parallel.make_mesh({"dp": 4, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    sharding = mx.parallel.shard_batch(mesh, "dp")
    x = jax.device_put(np.zeros((8, 4), dtype=np.float32), sharding)
    assert len(x.sharding.device_set) == 8

    with mx.parallel.with_mesh(mesh):
        assert mx.parallel.current_mesh() is mesh
    assert mx.parallel.current_mesh() is None


def test_spmd_psum_gradient_correctness():
    """Gradients from the sharded executor must equal the single-device
    gradients exactly (the psum XLA inserts = CommDevice::Reduce)."""
    X = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    Y = np.zeros(32, dtype=np.float32)
    net = _mlp()

    grads = {}
    for ctxs in [[mx.cpu()], [mx.cpu(i) for i in range(8)]]:
        mod = mx.mod.Module(net, context=ctxs)
        mod.bind(
            data_shapes=[("data", (32, 8))],
            label_shapes=[("softmax_label", (32,))],
        )
        mx.random.seed(1)
        mod.init_params(initializer=mx.init.Uniform(0.1), force_init=True)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(X)], label=[mx.nd.array(Y)]
        )
        mod.forward_backward(batch)
        exe = mod._exec_group._exec
        grads[len(ctxs)] = {
            n: exe.grad_dict[n].asnumpy() for n in exe.grad_dict
        }
    for name in grads[1]:
        assert_almost_equal(
            grads[1][name], grads[8][name], rtol=1e-4, atol=1e-6,
            names=(f"1dev:{name}", f"8dev:{name}"),
        )


def test_model_parallel_ctx_group_accepted():
    """group2ctx placement (reference test_model_parallel.py) — attr plumbing
    works; sharded placement is a TODO recorded in the executor."""
    with mx.AttrScope(ctx_group="dev1"):
        a = mx.sym.Variable("a")
    with mx.AttrScope(ctx_group="dev2"):
        b = mx.sym.Variable("b")
    c = a + b
    exe = c.bind(
        mx.cpu(),
        args={"a": mx.nd.ones((2,)), "b": mx.nd.ones((2,))},
        group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
    )
    exe.forward()
    assert_almost_equal(exe.outputs[0].asnumpy(), [2, 2])
