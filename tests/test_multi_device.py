"""Multi-device tests on the 8-device virtual CPU mesh
(reference test_multi_device_exec.py, test_model_parallel.py, and the
distributed-semantics strategy of SURVEY.md §4: process-level fakes)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def _toy(n=512, d=16, k=3, seed=42):
    r = np.random.RandomState(seed)
    W = r.randn(d, k)
    X = r.randn(n, d).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.float32)
    return X, Y


def _mlp(k=3):
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=24, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=k, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_eight_device_data_parallel_converges():
    X, Y = _toy()
    train = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(X, Y, batch_size=64)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(8)])
    mod.fit(
        train, optimizer="sgd", optimizer_params={"learning_rate": 0.5},
        num_epoch=10, initializer=mx.init.Xavier(),
    )
    acc = mod.score(val, "acc")[0][1]
    assert acc > 0.9

    exe = mod._exec_group._exec
    # data sharded over dp, params replicated (XLA inserts the psum)
    assert str(exe.arg_dict["data"]._data.sharding.spec) == "PartitionSpec('dp',)"
    assert str(exe.arg_dict["fc1_weight"]._data.sharding.spec) == "PartitionSpec()"


def test_multi_device_matches_single_device():
    """DP over 8 devices must produce identical updates to 1 device
    (the reference's convergence-parity claim, BASELINE.md)."""
    X, Y = _toy(n=128)
    params = {}
    for ctxs in [[mx.cpu()], [mx.cpu(i) for i in range(8)]]:
        mx.random.seed(3)
        train = mx.io.NDArrayIter(X, Y, batch_size=32)
        mod = mx.mod.Module(_mlp(), context=ctxs)
        mod.fit(
            train, optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            num_epoch=2, initializer=mx.init.Uniform(0.05),
        )
        arg_params, _ = mod.get_params()
        params[len(ctxs)] = {k: v.asnumpy() for k, v in arg_params.items()}
    for k in params[1]:
        assert_almost_equal(
            params[1][k], params[8][k], rtol=1e-4, atol=1e-5,
            names=(f"1dev:{k}", f"8dev:{k}"),
        )


def test_mesh_helpers():
    import jax

    mesh = mx.parallel.make_mesh({"dp": 4, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    sharding = mx.parallel.shard_batch(mesh, "dp")
    x = jax.device_put(np.zeros((8, 4), dtype=np.float32), sharding)
    assert len(x.sharding.device_set) == 8

    with mx.parallel.with_mesh(mesh):
        assert mx.parallel.current_mesh() is mesh
    assert mx.parallel.current_mesh() is None


def test_spmd_psum_gradient_correctness():
    """Gradients from the sharded executor must equal the single-device
    gradients exactly (the psum XLA inserts = CommDevice::Reduce)."""
    X = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    Y = np.zeros(32, dtype=np.float32)
    net = _mlp()

    grads = {}
    for ctxs in [[mx.cpu()], [mx.cpu(i) for i in range(8)]]:
        mod = mx.mod.Module(net, context=ctxs)
        mod.bind(
            data_shapes=[("data", (32, 8))],
            label_shapes=[("softmax_label", (32,))],
        )
        mx.random.seed(1)
        mod.init_params(initializer=mx.init.Uniform(0.1), force_init=True)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(X)], label=[mx.nd.array(Y)]
        )
        mod.forward_backward(batch)
        exe = mod._exec_group._exec
        grads[len(ctxs)] = {
            n: exe.grad_dict[n].asnumpy() for n in exe.grad_dict
        }
    for name in grads[1]:
        assert_almost_equal(
            grads[1][name], grads[8][name], rtol=1e-4, atol=1e-6,
            names=(f"1dev:{name}", f"8dev:{name}"),
        )


def test_model_parallel_chain():
    """Port of reference test_model_parallel.py:12-40 (test_chain): a graph
    split across two ctx groups must match the single-device run in both
    outputs and gradients, AND intermediates must actually execute on the
    assigned (virtual CPU) devices."""
    import numpy as np

    shape = (4, 5)
    data1 = mx.sym.Variable("data1")
    data2 = mx.sym.Variable("data2")
    with mx.AttrScope(ctx_group="dev1"):
        net = data1 + data2
        net = net * 3.0
    with mx.AttrScope(ctx_group="dev2"):
        net = net + data1

    arr = [mx.nd.ones(shape), mx.nd.ones(shape) * 2]
    arr_grad = [mx.nd.zeros(shape), mx.nd.zeros(shape)]
    exec1 = net.bind(
        mx.cpu(),
        args=arr,
        args_grad=arr_grad,
        group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
    )
    # the PlaceDevice lowering actually assigned distinct devices
    devs = set(d.id for d in exec1._node2dev.values())
    assert devs == {0, 1}, f"placement map wrong: {devs}"

    arr2 = [a.copyto(mx.cpu()) for a in arr]
    arr_grad2 = [a.copyto(mx.cpu()) for a in arr_grad]
    exec2 = net.bind(mx.cpu(), args=arr2, args_grad=arr_grad2)

    exec1.forward(is_train=True)
    exec2.forward(is_train=True)
    out1 = exec1.outputs[0]
    # the head output was computed by the dev2-placed node → lives on cpu(1)
    out_dev = list(out1._data.devices())[0]
    assert out_dev.id == 1, f"output on {out_dev}, expected cpu(1)"
    assert_almost_equal(out1.asnumpy(), exec2.outputs[0].asnumpy())

    out_grad = mx.nd.ones(shape, ctx=mx.cpu(1))
    exec1.backward([out_grad])
    exec2.backward([out_grad.copyto(mx.cpu())])
    for g1, g2 in zip(exec1.grad_arrays, exec2.grad_arrays):
        assert_almost_equal(g1.asnumpy(), g2.asnumpy())
    # d/d(data1) of (3*(data1+data2) + data1) = 4, d/d(data2) = 3
    assert_almost_equal(exec1.grad_arrays[0].asnumpy(), np.full(shape, 4.0))
    assert_almost_equal(exec1.grad_arrays[1].asnumpy(), np.full(shape, 3.0))


def test_tensor_parallel_mlp_matches_unsharded():
    """Megatron-style tp MLP over a (dp=2, tp=2) mesh: forward + grads must
    match the unsharded math, and the hidden activation must be tp-sharded
    (XLA inserts the closing psum)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mxnet_tpu.parallel import make_mesh, tp_mlp

    mesh = make_mesh({"dp": 2, "tp": 2}, backend="cpu")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 12).astype(np.float32))
    w1 = jnp.asarray(rng.randn(24, 12).astype(np.float32) * 0.2)
    w2 = jnp.asarray(rng.randn(12, 24).astype(np.float32) * 0.2)

    def loss(w1v, w2v):
        return jnp.sum(tp_mlp(x, w1v, w2v, mesh, dp_axis="dp") ** 2)

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(w1, w2)

    def ref_loss(w1v, w2v):
        return jnp.sum((jax.nn.gelu(x @ w1v.T) @ w2v.T) ** 2)

    ref_val, ref_grads = jax.value_and_grad(ref_loss, argnums=(0, 1))(w1, w2)
    assert_almost_equal(float(val), float(ref_val), rtol=1e-4)
    for g, rg in zip(grads, ref_grads):
        assert_almost_equal(np.asarray(g), np.asarray(rg), rtol=1e-4,
                            atol=1e-5)
    # the computation must actually be tensor-parallel: the row-parallel
    # contraction forces an all-reduce in the compiled program
    hlo = (
        jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))
        .lower(w1, w2).compile().as_text()
    )
    assert "all-reduce" in hlo, "no all-reduce: tp sharding was dropped"


def test_model_parallel_diamond_join():
    """A node with no ctx_group joining two placed branches runs on the bind
    context (reference AssignContext default) instead of crashing."""
    import numpy as np

    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    with mx.AttrScope(ctx_group="dev1"):
        x = a * 2.0
    with mx.AttrScope(ctx_group="dev2"):
        y = b * 3.0
    c = x + y  # unannotated join
    exe = c.bind(
        mx.cpu(0),
        args={"a": mx.nd.ones((2, 2)), "b": mx.nd.ones((2, 2))},
        group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
    )
    exe.forward()
    assert_almost_equal(exe.outputs[0].asnumpy(), np.full((2, 2), 5.0))


def test_model_parallel_training_converges():
    """A ctx-group-split MLP trained with manually bound executors converges
    (the reference's model-parallel pattern, example/model-parallel-lstm)."""
    import numpy as np

    rng = np.random.RandomState(0)
    X = rng.randn(64, 10).astype(np.float32)
    W = rng.randn(10, 3).astype(np.float32)
    Y = X.dot(W).argmax(axis=1).astype(np.float32)

    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="dev1"):
        h = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        h = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
    out = mx.sym.SoftmaxOutput(h, name="softmax")

    exe = out.simple_bind(
        mx.cpu(), data=(16, 10), softmax_label=(16,),
        grad_req={n: "write" for n in out.list_arguments() if n != "data"
                  and n != "softmax_label"},
        group2ctx={"dev1": mx.cpu(2), "dev2": mx.cpu(3)},
    )
    assert set(d.id for d in exe._node2dev.values()) >= {2, 3}
    mx.random.seed(7)
    init = mx.init.Xavier()
    for n, arr in exe.arg_dict.items():
        if n not in ("data", "softmax_label"):
            init(mx.init.InitDesc(n), arr)
    correct = total = 0
    for epoch in range(12):
        correct = total = 0
        for b in range(0, 64, 16):
            exe.arg_dict["data"][:] = mx.nd.array(X[b:b + 16])
            exe.arg_dict["softmax_label"][:] = mx.nd.array(Y[b:b + 16])
            exe.forward(is_train=True)
            exe.backward()
            pred = exe.outputs[0].asnumpy().argmax(axis=1)
            correct += (pred == Y[b:b + 16]).sum()
            total += 16
            for n in exe.grad_dict:
                mx.nd.sgd_update(
                    exe.arg_dict[n], exe.grad_dict[n], out=exe.arg_dict[n],
                    lr=0.1, wd=0.0,
                )
    assert correct / total > 0.9, f"model-parallel training stuck: {correct/total}"


def _tp_mlp_symbol(hidden=32, k=3):
    """MLP with a Megatron column->row parallel pair, built purely through
    the Symbol API + AttrScope (the user-facing TP path)."""
    net = mx.sym.Variable("data")
    with mx.AttrScope(__shard__="tp:0"):  # column-parallel: out dim sharded
        net = mx.sym.FullyConnected(net, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    with mx.AttrScope(__shard__="tp:1"):  # row-parallel: in dim sharded
        net = mx.sym.FullyConnected(net, num_hidden=k, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_symbol_level_tensor_parallel_shards_and_matches():
    """A Symbol-built model TP-shards through Module with no raw-jax code,
    and training matches the unsharded run exactly (same rng/init)."""
    from mxnet_tpu import parallel

    X, Y = _toy(n=128)
    params = {}
    for mesh in [None, parallel.make_mesh({"dp": 2, "tp": 4})]:
        mx.random.seed(11)
        train = mx.io.NDArrayIter(X, Y, batch_size=32)
        sym = _tp_mlp_symbol()
        if mesh is None:
            mod = mx.mod.Module(sym, context=mx.cpu())
            mod.fit(train, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.1},
                    num_epoch=2, initializer=mx.init.Uniform(0.05))
        else:
            with parallel.with_mesh(mesh):
                mod = mx.mod.Module(sym, context=mx.cpu())
                mod.fit(train, optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1},
                        num_epoch=2, initializer=mx.init.Uniform(0.05))
            exe = mod._exec_group._exec
            # column-parallel weight (out, in): out dim over tp; its bias
            # (out,) shards dim 0 too; row-parallel fc2 shards dim 1, and
            # its 1-d bias replicates (spec dim outside rank)
            assert str(exe.arg_dict["fc1_weight"]._data.sharding.spec) == \
                "PartitionSpec('tp',)"
            assert str(exe.arg_dict["fc1_bias"]._data.sharding.spec) == \
                "PartitionSpec('tp',)"
            assert str(exe.arg_dict["fc2_weight"]._data.sharding.spec) == \
                "PartitionSpec(None, 'tp')"
            assert str(exe.arg_dict["fc2_bias"]._data.sharding.spec) in (
                "PartitionSpec()", "PartitionSpec(None,)")
            # data stays batch-sharded over dp — the scope must never leak
            # onto inputs flowing through the layer
            assert str(exe.arg_dict["data"]._data.sharding.spec) == \
                "PartitionSpec('dp',)"
        arg_params, _ = mod.get_params()
        params[mesh is None] = {k: v.asnumpy() for k, v in arg_params.items()}
    for k in params[True]:
        assert_almost_equal(params[True][k], params[False][k],
                            rtol=1e-4, atol=1e-5, names=(f"single:{k}", f"tp:{k}"))


def test_shard_spec_collection_and_overrides():
    from mxnet_tpu import parallel

    # explicit Variable attr wins over the consumer op's scope
    w = mx.sym.Variable("fc1_weight", __shard__="tp:1")
    data = mx.sym.Variable("data")
    with mx.AttrScope(__shard__="tp:0"):
        net = mx.sym.FullyConnected(data, weight=w, num_hidden=8, name="fc1")
    specs = parallel.collect_shard_specs(net)
    assert specs["fc1_weight"] == ("tp", 1)
    assert specs["fc1_bias"] == ("tp", 0)
    assert "data" in specs  # collected raw; binder applies to params only
    assert parallel.parse_shard_spec("dp") == ("dp", 0)
