"""Profiler + Monitor contracts (ISSUE 2 satellites): dump_profile's file
contract, graceful degradation when jax profiling is unavailable, and
Monitor.install/toc against a real executor."""

import gzip
import json
import logging
import os
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import profiler  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_profiler_state():
    saved = dict(profiler._state)
    saved_warned = set(profiler._warned)
    yield
    profiler._state.clear()
    profiler._state.update(saved)
    profiler._warned.clear()
    profiler._warned.update(saved_warned)


# ---------------------------------------------------------------------------
# dump_profile file contract
# ---------------------------------------------------------------------------
def test_dump_profile_extracts_gzipped_trace(tmp_path):
    """A logdir holding a nested *.trace.json.gz → its JSON lands at the
    configured filename (the reference's profile-file contract)."""
    logdir = tmp_path / "run_trace" / "plugins" / "profile" / "2026"
    logdir.mkdir(parents=True)
    payload = {"traceEvents": [{"name": "op", "ph": "X", "ts": 0, "dur": 1}]}
    with gzip.open(logdir / "host.trace.json.gz", "wt") as f:
        json.dump(payload, f)
    out = tmp_path / "profile.json"
    profiler.profiler_set_config(filename=str(out))
    profiler._state["logdir"] = str(tmp_path / "run_trace")
    assert profiler.dump_profile() == str(out)
    with open(out) as f:
        assert json.load(f) == payload


def test_dump_profile_empty_logdir_returns_none(tmp_path):
    profiler.profiler_set_config(filename=str(tmp_path / "p.json"))
    profiler._state["logdir"] = str(tmp_path)  # exists, holds no traces
    assert profiler.dump_profile() is None
    assert not os.path.exists(tmp_path / "p.json")


def test_dump_profile_without_any_trace_returns_none():
    profiler._state.pop("logdir", None)
    profiler._state["running"] = False
    assert profiler.dump_profile() is None


# ---------------------------------------------------------------------------
# graceful degradation when jax profiling is unavailable
# ---------------------------------------------------------------------------
def test_trace_annotation_noop_when_profiler_missing(monkeypatch, caplog):
    monkeypatch.setattr(profiler, "_jax_profiler", lambda: None)
    with caplog.at_level(logging.WARNING):
        with profiler.trace_annotation("region"):
            x = 1 + 1
    assert x == 2  # body ran, nothing raised


def test_trace_annotation_warns_once_on_broken_annotation(monkeypatch, caplog):
    class _Broken:
        class TraceAnnotation:
            def __init__(self, name):
                raise RuntimeError("no profiler plugin")

    monkeypatch.setattr(profiler, "_jax_profiler", lambda: _Broken)
    with caplog.at_level(logging.WARNING):
        with profiler.trace_annotation("a"):
            pass
        with profiler.trace_annotation("b"):
            pass
    warnings = [r for r in caplog.records if "TraceAnnotation" in r.message]
    assert len(warnings) == 1  # warn once, not per construction


def test_set_state_degrades_when_start_trace_fails(monkeypatch, caplog):
    class _Broken:
        @staticmethod
        def start_trace(logdir):
            raise RuntimeError("profiling disabled in this build")

    monkeypatch.setattr(profiler, "_jax_profiler", lambda: _Broken)
    with caplog.at_level(logging.WARNING):
        profiler.profiler_set_state("run")
    assert profiler._state["running"] is False
    assert any("start_trace failed" in r.message for r in caplog.records)


def test_autostart_never_raises(monkeypatch):
    monkeypatch.setenv("MXNET_PROFILER_AUTOSTART", "1")

    def _boom(state="stop"):
        raise RuntimeError("broken backend")

    monkeypatch.setattr(profiler, "profiler_set_state", _boom)
    profiler._maybe_autostart()  # must swallow, import must survive


def test_real_trace_annotation_usable():
    """On this build jax.profiler exists: the annotation context works."""
    with profiler.trace_annotation("tier1-region"):
        pass


# ---------------------------------------------------------------------------
# Monitor against a real executor
# ---------------------------------------------------------------------------
def _bound_module():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=4, name="fc1")
    net = mx.sym.SoftmaxOutput(h, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (4, 3))],
             label_shapes=[mx.io.DataDesc("softmax_label", (4,))])
    mod.init_params()
    return mod


def test_monitor_install_and_toc_on_executor():
    mon = mx.monitor.Monitor(interval=1, pattern=".*")
    mod = _bound_module()
    mod.install_monitor(mon)
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.uniform(size=(4, 3)).astype(np.float32))],
        label=[mx.nd.array(np.zeros(4, np.float32))])
    mon.tic()
    mod.forward(batch, is_train=True)
    records = mon.toc()
    assert records, "monitor saw no tensors from the executor"
    names = [name for _, name, _ in records]
    # per-op outputs flow through the callback AND toc sweeps the
    # executor's argument arrays (reference toc behaviour)
    assert any("fc1" in n or "softmax" in n for n in names)
    assert any("weight" in n for n in names)
    for _, _, stat in records:
        float(stat)  # default stat renders as a scalar string


def test_monitor_interval_and_toc_disarmed():
    mon = mx.monitor.Monitor(interval=2)
    mod = _bound_module()
    mod.install_monitor(mon)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(np.ones((4, 3), np.float32))],
        label=[mx.nd.array(np.zeros(4, np.float32))])
    mon.tic()  # batch 0: armed
    mod.forward(batch, is_train=True)
    assert mon.toc()
    mon.tic()  # batch 1: off-interval, disarmed
    mod.forward(batch, is_train=True)
    assert mon.toc() == []
