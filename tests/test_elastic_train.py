"""Elastic multi-host training: the kill/join chaos suite.

Real subprocess workers (tests/elastic_worker.py) drive ``Module.fit``
end-to-end over the elastic TCP kvstore (MXNET_KV_TRANSPORT=tcp). Ranks
are spawned DIRECTLY (not via tools/launch.py) so one rank's engineered
death doesn't trigger any launcher-level teardown — the point is that the
SURVIVORS finish on their own. Every leg asserts convergence within the
oracle loss tolerance plus the membership counters that prove the
machinery (not luck) carried the run.

All legs are slow-marked: tier-1 keeps its alphabetical-prefix budget, and
``-m chaos`` selects the suite alone.
"""

import os
import re
import signal
import socket
import subprocess
import sys
import time

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_WORKER = os.path.join(_ROOT, "tests", "elastic_worker.py")

pytestmark = [pytest.mark.slow, pytest.mark.chaos, pytest.mark.sanitize]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _base_env(num_workers, ps_port, **extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        "MXNET_KV_TRANSPORT": "tcp",
        "MXNET_COORDINATOR": f"127.0.0.1:{_free_port()}",
        "MXNET_PS_PORT": str(ps_port),
        "MXNET_NUM_PROCS": str(num_workers),
        "MXNET_KV_HEARTBEAT_MS": "200",
        "MXNET_KV_PEER_TIMEOUT": "3",
        "MXNET_KV_RECONNECT": "30",
        "MXNET_KV_TIMEOUT": "120",  # any hang becomes a typed exit 41
        "MXNET_PS_EXIT_TIMEOUT": "15",
    })
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _spawn(rank, env, **extra):
    e = dict(env)
    e["MXNET_PROC_ID"] = str(rank)
    e.update({k: str(v) for k, v in extra.items()})
    return subprocess.Popen(
        [sys.executable, _WORKER], env=e,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _finish(proc, timeout):
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        raise AssertionError(
            f"worker pid {proc.pid} hung (zero-hang guarantee violated):\n"
            f"{out[-4000:]}")
    return out


def _stat(out, name):
    m = re.search(rf"ELASTIC-STATS .*\b{name}=(\d+)", out)
    assert m, f"no {name} in ELASTIC-STATS:\n{out[-4000:]}"
    return int(m.group(1))


def test_kill_one_mid_epoch_survivor_converges_at_dp_minus_1():
    """Chaos leg 1: rank 1 hard-dies (faultinject os._exit, no LEAVE, no
    cleanup) mid-epoch. The survivor must detect the death by heartbeat
    silence, reshard to dp-1 at the next fence, keep training, and land
    within the oracle loss tolerance — with the counters to prove the
    path taken."""
    env = _base_env(2, _free_port())
    p0 = _spawn(0, env)
    p1 = _spawn(1, env, MXNET_FI_KV_KILL_RANK=1, MXNET_FI_KV_KILL_AT_BATCH=6,
                ELASTIC_SKIP_ASSERT=1)
    out1 = _finish(p1, 180)
    assert p1.returncode == 17, f"rank 1 rc={p1.returncode}:\n{out1[-2000:]}"
    assert "KV-KILL rank 1 at train batch 6" in out1, out1[-2000:]
    out0 = _finish(p0, 300)
    assert p0.returncode == 0, f"survivor rc={p0.returncode}:\n{out0[-4000:]}"
    assert "rank 0 ELASTIC-TRAIN OK" in out0, out0[-4000:]
    # counter-verified: the death was DETECTED and the membership epoch
    # advanced through a fenced reshard — not a silent lucky run
    assert _stat(out0, "peer_dead") >= 1, out0[-4000:]
    assert _stat(out0, "reshard") >= 1, out0[-4000:]
    assert _stat(out0, "membership_epoch") >= 3, out0[-4000:]
    assert _stat(out0, "membership_size") == 1, out0[-4000:]


def test_worker_joins_at_next_fence():
    """Chaos leg 2: a worker added mid-run is admitted at the next fence;
    incumbents observe the membership event, reshard to dp+1, and keep
    training to convergence. The joiner fast-forwards onto the live round
    line and finishes cleanly."""
    env = _base_env(2, _free_port(), ELASTIC_BATCH_SLEEP="0.05",
                    ELASTIC_EPOCHS="40")
    p0 = _spawn(0, env)
    p1 = _spawn(1, env)
    time.sleep(4)  # let the incumbents get well into training
    p2 = _spawn(2, env, MXNET_NUM_PROCS=3, ELASTIC_SKIP_ASSERT=1,
                ELASTIC_EPOCHS=10)
    out2 = _finish(p2, 240)
    out0 = _finish(p0, 240)
    out1 = _finish(p1, 240)
    assert p2.returncode == 0, f"joiner rc={p2.returncode}:\n{out2[-4000:]}"
    assert p0.returncode == 0, f"rank 0 rc={p0.returncode}:\n{out0[-4000:]}"
    assert p1.returncode == 0, f"rank 1 rc={p1.returncode}:\n{out1[-4000:]}"
    assert "rank 0 ELASTIC-TRAIN OK" in out0
    assert "rank 1 ELASTIC-TRAIN OK" in out1
    # incumbents saw the join as a membership event and fenced through it
    assert _stat(out0, "membership_join") >= 1
    assert _stat(out0, "reshard") >= 1, out0[-4000:]
    assert _stat(out1, "reshard") >= 1, out1[-4000:]


def test_coordinator_restart_recovers_via_reseed():
    """Chaos leg 3: rank 0 — the membership coordinator itself — dies and
    is relaunched (same rank, MXNET_NUM_RESTARTS bumped). The survivor
    detects the fresh server incarnation (boot nonce), re-seeds the master
    weights from its live params, and BOTH ranks finish within
    tolerance."""
    ps_port = _free_port()
    env = _base_env(2, ps_port)
    p0 = _spawn(0, env, MXNET_FI_KV_KILL_RANK=0, MXNET_FI_KV_KILL_AT_BATCH=6,
                MXNET_FI_ATTEMPT=0, ELASTIC_SKIP_ASSERT=1)
    p1 = _spawn(1, env, MXNET_FI_ATTEMPT=0)
    out0 = _finish(p0, 180)
    assert p0.returncode == 17, f"rank 0 rc={p0.returncode}:\n{out0[-2000:]}"
    # supervised per-rank restart: same rank id, restart count bumped so
    # the kill schedule (pinned to attempt 0) does not re-fire
    p0b = _spawn(0, env, MXNET_FI_KV_KILL_RANK=0,
                 MXNET_FI_KV_KILL_AT_BATCH=6, MXNET_FI_ATTEMPT=0,
                 MXNET_NUM_RESTARTS=1, ELASTIC_SKIP_ASSERT=1)
    out1 = _finish(p1, 300)
    out0b = _finish(p0b, 300)
    assert p1.returncode == 0, f"survivor rc={p1.returncode}:\n{out1[-4000:]}"
    assert p0b.returncode == 0, \
        f"restarted rank 0 rc={p0b.returncode}:\n{out0b[-4000:]}"
    assert "rank 1 ELASTIC-TRAIN OK" in out1, out1[-4000:]
    # the survivor re-seeded the restarted coordinator's empty store from
    # its live parameters instead of training from scratch (or hanging)
    assert _stat(out1, "elastic_reseed") >= 1, out1[-4000:]


def test_compression_trains_within_tolerance():
    """Straggler-mitigation leg: int8 gradient compression with error
    feedback trains to the same oracle tolerance; the compression path is
    counter-verified on every rank."""
    env = _base_env(2, _free_port(), MXNET_KV_COMPRESS="int8")
    p0 = _spawn(0, env)
    p1 = _spawn(1, env)
    out0 = _finish(p0, 300)
    out1 = _finish(p1, 300)
    assert p0.returncode == 0, f"rank 0 rc={p0.returncode}:\n{out0[-4000:]}"
    assert p1.returncode == 0, f"rank 1 rc={p1.returncode}:\n{out1[-4000:]}"
    assert "rank 0 ELASTIC-TRAIN OK" in out0
    assert "rank 1 ELASTIC-TRAIN OK" in out1
    assert _stat(out0, "compress_push") > 0
    assert _stat(out1, "compress_push") > 0


def test_tcp_watchdog_converts_stall_to_exit_41(tmp_path):
    """Zero-hang guarantee, elastic plane: a peer that heartbeats (alive)
    but never contributes to a round stalls the survivor's blocking pull;
    the PR-4 watchdog must convert that into a diagnosed exit 41 instead
    of an unbounded hang. (Mesh-plane twin: test_watchdog_stall below.)"""
    script = str(tmp_path / "stall.py")
    with open(script, "w") as f:
        f.write(
            "import os, time\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import numpy as np\n"
            "import mxnet_tpu as mx\n"
            "kv = mx.kv.create('dist_sync')\n"
            "kv.init(0, mx.nd.array(np.zeros(2, np.float32)))\n"
            "if kv.rank == 0:\n"
            "    kv.push(0, mx.nd.array(np.ones(2, np.float32)))\n"
            "    o = mx.nd.array(np.zeros(2, np.float32))\n"
            "    kv.pull(0, out=o)  # blocks: rank 1 never pushes\n"
            "    print('rank 0 unexpectedly unblocked', flush=True)\n"
            "else:\n"
            "    time.sleep(60)  # heartbeating, never pushing\n"
        )
    env = _base_env(2, _free_port(), MXNET_KV_TIMEOUT="5",
                    MXNET_KV_PEER_TIMEOUT="600")
    procs = [subprocess.Popen(
        [sys.executable, script],
        env={**env, "MXNET_PROC_ID": str(r)},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    out0 = _finish(procs[0], 120)
    assert procs[0].returncode == 41, \
        f"rc={procs[0].returncode}:\n{out0[-3000:]}"
    assert "blocked in 'elastic pull'" in out0, out0[-3000:]
    procs[1].send_signal(signal.SIGTERM)
    procs[1].wait(timeout=30)


def test_elastic_launcher_restarts_single_rank(tmp_path):
    """launch.py --elastic: a dead rank is relaunched ALONE with its old
    rank id and a bumped per-rank MXNET_NUM_RESTARTS, while the other
    ranks are left untouched (contrast: the mesh plane's whole-job
    restart)."""
    marker = str(tmp_path / "died_once")
    script = str(tmp_path / "flaky.py")
    with open(script, "w") as f:
        f.write(
            "import os, sys, time\n"
            f"marker = {marker!r}\n"
            "rank = os.environ['MXNET_PROC_ID']\n"
            "assert os.environ['MXNET_KV_TRANSPORT'] == 'tcp'\n"
            "if rank == '1' and not os.path.exists(marker):\n"
            "    open(marker, 'w').close()\n"
            "    sys.exit(3)  # simulated crash on first life\n"
            "time.sleep(1)  # outlive the relaunch so lives overlap\n"
            "nr = os.environ['MXNET_NUM_RESTARTS']\n"
            "print(f'rank {rank} alive restarts={nr}', flush=True)\n"
        )
    env = dict(os.environ)
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", "2", "--launcher", "local", "--port", str(_free_port()),
        "--elastic", "--max-restarts", "1",
        sys.executable, script,
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    assert "per-rank restart (attempt 1, budget 1/1)" in out, out
    # rank 0 was never restarted; rank 1's second life sees its own count
    assert "rank 0 alive restarts=0" in out, out
    assert "rank 1 alive restarts=1" in out, out

    # with no restart budget the job fails and reports the dead rank
    os.unlink(marker)
    cmd[cmd.index("--max-restarts") + 1] = "0"
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=120)
    out = proc.stdout + proc.stderr
    assert proc.returncode != 0
    assert "restart budget spent" in out


@pytest.mark.dist_multiprocess
def test_mesh_watchdog_converts_stall_to_exit_41():
    """Satellite: the PR-4 collective watchdog end-to-end on the MESH
    plane — rank 1 stalls before barrier 2, rank 0 blocks inside the XLA
    collective, and the watchdog exits 41 with the actionable diagnostic
    (supervisor then reports the dead rank)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_KV_TIMEOUT"] = "6"
    cmd = [
        sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
        "-n", "2", "--launcher", "local", "--port", str(_free_port()),
        sys.executable,
        os.path.join(_ROOT, "tests", "watchdog_stall_worker.py"),
    ]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=300)
    out = proc.stdout + proc.stderr
    assert proc.returncode != 0, out[-4000:]
    assert "blocked in 'barrier'" in out, out[-4000:]
    assert "rank 0 died (rc=41)" in out, out[-4000:]
