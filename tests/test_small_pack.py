"""Small-parameter packing (executor.py _small_state): hundreds of tiny
f32 tensors (BN scalars, biases, grads, momenta) ride ONE flat device
buffer per family across the training-program boundary. The oracle is
exact parity with the unpacked path, plus handle coherence under reads
and user writes."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

BATCH = 8


def _bn_net(nlayer=6):
    h = mx.sym.Variable("data")
    for i in range(nlayer):
        h = mx.sym.FullyConnected(h, num_hidden=16, name=f"fc{i}")
        h = mx.sym.BatchNorm(h, fix_gamma=False, name=f"bn{i}")
        h = mx.sym.Activation(h, act_type="relu", name=f"act{i}")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="out")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _train(mod, x, y, steps):
    for s in range(steps):
        b = mx.io.DataBatch(
            data=[mx.nd.array(x[s % 4])], label=[mx.nd.array(y[s % 4])])
        mod.forward_backward(b)
        mod.update()


def _build(seed=3):
    mx.random.seed(seed)
    np.random.seed(seed)
    mod = mx.mod.Module(_bn_net(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, 12))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    return mod


def _data(seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(4, BATCH, 12).astype(np.float32)
    y = rs.randint(0, 4, (4, BATCH)).astype(np.float32)
    return x, y


def test_packing_activates_and_matches_unpacked(monkeypatch):
    x, y = _data()

    mod = _build()
    _train(mod, x, y, 12)
    exe = mod._exec_group._exec
    small = exe._small_state()
    assert small is not None and small["arg"] is not None, \
        "packing did not activate on a BN-heavy net"
    assert len(small["arg"]["names"]) >= 12  # gammas/betas/biases
    args_packed, auxs_packed = mod.get_params()

    monkeypatch.setenv("MXNET_PACK_SMALL_PARAMS", "0")
    mod2 = _build()
    assert mod2._exec_group._exec._small_state() is None
    _train(mod2, x, y, 12)
    args_ref, auxs_ref = mod2.get_params()

    for n in args_ref:
        assert_almost_equal(args_packed[n].asnumpy(), args_ref[n].asnumpy(),
                            rtol=1e-5, atol=1e-6, names=(n, n))
    for n in auxs_ref:
        assert_almost_equal(auxs_packed[n].asnumpy(), auxs_ref[n].asnumpy(),
                            rtol=1e-5, atol=1e-6, names=(n, n))


def test_packed_handles_stay_coherent_under_user_writes():
    x, y = _data(1)
    mod = _build()
    _train(mod, x, y, 4)
    exe = mod._exec_group._exec
    small = exe._small_state()
    assert small and small["arg"]
    name = small["arg"]["names"][0]

    # read-through: handle value equals the packed slice
    before = exe.arg_dict[name].asnumpy()
    assert before.shape == small["arg"]["offs"][name][2]

    # user write between steps must survive and flow into training
    exe.arg_dict[name][:] = 7.5
    _train(mod, x, y, 1)
    after = exe.arg_dict[name].asnumpy()
    assert not np.allclose(after, before)  # update moved it off 7.5
    assert np.allclose(after, 7.5, atol=1.0), after  # ...from 7.5, not old

    # set_params full-checkpoint restore stays exact
    args, auxs = mod.get_params()
    mod.set_params({k: v.copy() for k, v in args.items()},
                   {k: v.copy() for k, v in auxs.items()}, force_init=True)
    args2, _ = mod.get_params()
    for n in args:
        assert_almost_equal(args2[n].asnumpy(), args[n].asnumpy(),
                            rtol=1e-6, atol=1e-7)


def test_packed_training_converges():
    rs = np.random.RandomState(0)
    w = rs.randn(12, 4).astype(np.float32)
    data = rs.randn(256, 12).astype(np.float32)
    label = np.argmax(data @ w, axis=1).astype(np.float32)
    mx.random.seed(0)
    np.random.seed(0)
    mod = mx.mod.Module(_bn_net(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, 12))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.005})
    metric = mx.metric.Accuracy()
    for epoch in range(40):
        metric.reset()
        for i in range(0, 256, BATCH):
            b = mx.io.DataBatch(data=[mx.nd.array(data[i:i + BATCH])],
                                label=[mx.nd.array(label[i:i + BATCH])])
            mod.forward_backward(b)
            mod.update()
            mod.update_metric(metric, b.label)
    assert mod._exec_group._exec._small_state() is not None
    assert metric.get()[1] > 0.9, metric.get()


def test_packed_grad_reads_fresh_every_step():
    """Regression: reading a packed gradient must (a) return the value the
    just-ran program produced — the read that TRIGGERS materialization must
    chain into the pack thunk — and (b) stay fresh on later steps even
    when the handle was not read in between (backward() re-arms the lazy
    each step)."""
    x, y = _data(2)
    mod = _build()
    exe = mod._exec_group._exec
    b = mx.io.DataBatch(data=[mx.nd.array(x[0])], label=[mx.nd.array(y[0])])
    mod.forward(b, is_train=True)
    mod.backward()  # NON-fused path: grads come from _materialize_backward
    small = exe._small_state()
    assert small and small["grad"]
    name = small["grad"]["names"][0]
    g1 = exe.grad_dict[name].asnumpy()
    assert np.abs(g1).sum() > 0, "triggering read returned stale zeros"
    mod.update()

    # two fused steps without reading, then the grad must be CURRENT
    _train(mod, x, y, 2)
    g2 = exe.grad_dict[name].asnumpy()
    b2 = mx.io.DataBatch(data=[mx.nd.array(x[3])], label=[mx.nd.array(y[3])])
    mod.forward(b2, is_train=True)
    mod.backward()
    g3 = exe.grad_dict[name].asnumpy()
    assert not np.allclose(g2, g3), "packed grad went permanently stale"


def test_failed_step_invalidation_semantics():
    """A trace-time failure (nothing donated) must leave packs intact and
    params readable; the loud-invalidation error must REPEAT on re-reads,
    never decay into serving stale values."""
    x, y = _data(4)
    mod = _build()
    _train(mod, x, y, 3)
    exe = mod._exec_group._exec
    small = exe._small_state()
    assert small and small["arg"]
    name = small["arg"]["names"][0]

    # trace/compile failure: fabricate by requesting a fused update with a
    # broken apply_fn through the raw interface
    import jax

    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x[0])],
                                label=[mx.nd.array(y[0])]), is_train=True)
    mod.backward()

    def broken_apply(i, w, g, s, lr, wd, t, rng):
        raise RuntimeError("boom at trace time")

    leaves, td = jax.tree_util.tree_flatten(
        [mx.nd.zeros(exe.arg_dict[n].shape)._data
         for n in [name]])
    with pytest.raises(Exception):
        exe.fused_train_update([name], broken_apply, (leaves, td),
                               [0.1], [0.0], [1], cache_token="broken")
    # nothing was donated: the pack survives, params stay readable
    assert small["arg"]["flat"] is not None
    _ = exe.arg_dict[name].asnumpy()

    # simulate a post-dispatch failure: invalidation must be sticky
    small["arg"]["flat"] = None
    from mxnet_tpu.base import MXNetError

    fresh = small["arg"]["names"][1]
    if exe.arg_dict[fresh]._lazy is not None:
        with pytest.raises(MXNetError, match="invalidated"):
            exe.arg_dict[fresh].asnumpy()
        with pytest.raises(MXNetError, match="invalidated"):
            exe.arg_dict[fresh].asnumpy()  # second read: same loud error


def test_packed_reshape_and_optimizer_state_roundtrip(tmp_path):
    """Two packing edge paths: (a) executor reshape (the bucketing path)
    must keep packed params coherent across the shape change; (b)
    optimizer-state save/load mid-training must serialize the CURRENT
    packed momentum values and training must resume exactly."""
    x, y = _data(5)
    mod = _build()
    _train(mod, x, y, 6)
    exe = mod._exec_group._exec
    assert exe._small_state() is not None

    # (a) reshape to a different batch, keep training
    b2 = mx.io.DataBatch(
        data=[mx.nd.array(np.random.RandomState(8).randn(
            BATCH * 2, 12).astype(np.float32))],
        label=[mx.nd.array(np.zeros(BATCH * 2, np.float32))])
    mod.forward(b2, is_train=True)
    mod.backward()
    mod.update()
    assert mod.get_outputs()[0].shape[0] == BATCH * 2

    # (b) save params + optimizer states, train on, restore, retrain:
    # the two continuations must be bit-identical
    prefix = str(tmp_path / "ck")
    mod.save_checkpoint(prefix, 0, save_optimizer_states=True)
    _train(mod, x, y, 3)
    cont_a, _ = mod.get_params()
    cont_a = {k: v.asnumpy() for k, v in cont_a.items()}

    mod2 = _build()
    _sym, args, auxs = mx.model.load_checkpoint(prefix, 0)
    mod2.set_params(args, auxs, force_init=True)
    mod2.load_optimizer_states(prefix + "-0000.states")
    _train(mod2, x, y, 3)
    cont_b, _ = mod2.get_params()
    for n, va in cont_a.items():
        assert_almost_equal(va, cont_b[n].asnumpy(), rtol=1e-5, atol=1e-6,
                            names=(f"a:{n}", f"b:{n}"))
