"""Environment-robustness ledger: a bare nested interpreter — spawned the
way the compiled C clients spawn embedded CPython, with NONE of the test
process's environment — must reach a working ``import jax`` promptly.

This is the regression fence for the axon-env drift class of failure
(VERDICT r5): the bench deployment's sitecustomize dials the single-chip
tunnel at interpreter boot whenever the ``PALLAS_AXON_*`` pool vars are
set, so a child inheriting them from a chip-holding parent spins in the
chip-claim retry loop until timeout (the 300 s hang). conftest.py scrubs
those vars from the pytest process; THIS test pins the contract from the
other side — an interpreter with a minimal, explicitly-constructed
environment initialises jax on CPU within the budget, so the next drift
of this kind fails the suite instead of hanging the C-client tests.
"""

import os
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.lint  # rides with the static-invariant suite

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: seconds a bare interpreter gets to import + use jax on CPU. Importing
#: jax cold takes a few seconds; the failure mode being fenced is a HANG
#: (chip-claim retry loop), which is minutes — the gap is unambiguous.
IMPORT_BUDGET_S = 120


def _bare_env(**extra):
    """The environment a C client's embedded interpreter effectively has:
    PATH/HOME only — no MXNET_*, no PALLAS_AXON_*, no JAX_* inherited."""
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/tmp"),
        "JAX_PLATFORMS": "cpu",
    }
    env.update(extra)
    return env


def _timed_run(code, env):
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=IMPORT_BUDGET_S)
    return proc, time.monotonic() - t0


def test_bare_interpreter_reaches_jax_within_budget():
    code = (
        "import jax, jax.numpy as jnp\n"
        "print(int(jnp.add(20, 22)), jax.default_backend())\n"
    )
    try:
        proc, elapsed = _timed_run(code, _bare_env())
    except subprocess.TimeoutExpired:
        pytest.fail(
            f"bare interpreter did not reach `import jax` within "
            f"{IMPORT_BUDGET_S} s — env drift is making nested "
            "interpreters hang at backend init again (axon-class bug)")
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.split()[0] == "42"
    assert elapsed < IMPORT_BUDGET_S


def test_bare_interpreter_imports_the_framework():
    """Same fence one layer up: ``import mxnet_tpu`` (what the C shim's
    embedded interpreter actually runs) from a bare env must work — it
    must not require launcher-exported rank/coordinator state."""
    code = "import mxnet_tpu as mx; print(mx.nd.array([1.0])[0:1].shape)"
    try:
        proc, _ = _timed_run(code, _bare_env(PYTHONPATH=ROOT))
    except subprocess.TimeoutExpired:
        pytest.fail(
            f"bare `import mxnet_tpu` exceeded {IMPORT_BUDGET_S} s — "
            "package import is blocking on environment it must not need")
    assert proc.returncode == 0, proc.stderr
    assert "(1,)" in proc.stdout
