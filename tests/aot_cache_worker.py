"""Subprocess driver for the persistent AOT-cache contract test.

Binds the bench-model family (model-zoo resnet18 at a small smoke shape)
in a FRESH process against a cache another process populated
(tools/aot_warm.py), exercises every steady-state program — train-step
gradients, the fused train update, eval forward — and prints the compile
counters as one JSON line. The parent asserts ``executor.jit_compile == 0``
and ``aot.cache_hit > 0``: a warm process must never touch XLA.

Run by tests/test_aot_cache.py with JAX_PLATFORMS=cpu and axon env vars
scrubbed (the established subprocess pattern).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models
import mxnet_tpu.telemetry as tm


def main():
    batch, image = 2, (3, 32, 32)
    sym = models.resnet(num_classes=10, num_layers=18,
                        image_shape=",".join(map(str, image)))
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", (batch,) + image)],
             label_shapes=[mx.io.DataDesc("softmax_label", (batch,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})
    rng = np.random.RandomState(0)
    b = mx.io.DataBatch(
        data=[mx.nd.array(rng.uniform(-1, 1, (batch,) + image)
                          .astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 10, (batch,))
                           .astype(np.float32))],
    )
    # train-step program: gradients read before update() materialize the
    # fused fwd+bwd (then the per-param update path consumes them)
    mod.forward_backward(b)
    grad = mod._exec_group._exec.grad_dict["fc1_weight"].asnumpy()
    mod.update()
    # fused train-update program (the steady-state training executable)
    mod.forward_backward(b)
    mod.update()
    # eval forward program
    mod.forward(b, is_train=False)
    out = mod.get_outputs()[0].asnumpy()
    print(json.dumps({
        "jit_compile": tm.counter("executor.jit_compile").value,
        "cache_hit": tm.counter("aot.cache_hit").value,
        "cache_miss": tm.counter("aot.cache_miss").value,
        "deserialize_error": tm.counter("aot.deserialize_error").value,
        "grad_norm": float(np.abs(grad).sum()),
        "out_shape": list(out.shape),
    }))


if __name__ == "__main__":
    main()
