"""Worker for the dist_async (hogwild parameter server) test.

Reference semantics (kvstore_dist_server.h async branch): each push applies
immediately server-side — no worker synchronization in the data path.
Every rank trains on its shard with update_on_kvstore semantics (push
grads, pull fresh weights); ranks progress at their own pace, and the
server's weights must still converge.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_async")
    rank, nw = kv.rank, kv.num_workers
    assert kv.type == "dist_async"

    rng = np.random.RandomState(42)
    X = rng.randn(256, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)
    Xs, Ys = X[rank::nw], Y[rank::nw]

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(Xs, Ys, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(7)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(
        kvstore=kv, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "rescale_grad": 1.0 / 16},
    )
    # async contract: the module must be updating ON the kvstore (server
    # applies pushes immediately; no cross-worker barrier in the loop)
    assert mod._update_on_kvstore

    metric = mx.metric.Accuracy()
    for epoch in range(30):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
    acc = metric.get()[1]
    assert acc > 0.8, f"rank {rank}: async training stuck at {acc}"
    print(f"rank {rank}/{nw} ASYNC-TRAIN OK acc={acc:.3f}", flush=True)
    # NO barriers: ranks exit whenever they finish; the kvstore's exit
    # hook keeps rank 0's server alive until all workers reported done


if __name__ == "__main__":
    main()
