"""Sparse NDArray + sparse kernels.

Modeled on the reference's ``tests/python/unittest/test_sparse_ndarray.py``
and ``test_sparse_operator.py`` (sparse branch merged into 0.10.1).
"""

import os
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sparse_ndarray as sp
from mxnet_tpu.test_utils import assert_almost_equal, rand_ndarray


def _rsp_fixture(shape=(6, 3)):
    dense = np.zeros(shape, np.float32)
    rows = np.array([0, 2, 5])[: min(3, shape[0])]
    rng = np.random.RandomState(0)
    dense[rows] = rng.randn(len(rows), *shape[1:]).astype(np.float32)
    return dense, rows


def test_rsp_creation_and_dense():
    dense, rows = _rsp_fixture()
    arr = sp.row_sparse(dense[rows], rows, dense.shape)
    assert arr.stype == "row_sparse"
    assert arr.shape == dense.shape
    assert_almost_equal(arr.asnumpy(), dense)
    assert arr.indices.dtype == np.int32
    assert_almost_equal(arr.indices.asnumpy(), rows)
    assert_almost_equal(arr.values.asnumpy(), dense[rows])


def test_csr_creation_and_dense():
    dense = np.array([[1, 0, 2], [0, 0, 3], [4, 5, 6]], np.float32)
    indptr = [0, 2, 3, 6]
    indices = [0, 2, 2, 0, 1, 2]
    values = [1, 2, 3, 4, 5, 6]
    arr = sp.csr(values, indptr, indices, (3, 3))
    assert arr.stype == "csr"
    assert_almost_equal(arr.asnumpy(), dense)
    assert arr.indptr.dtype == np.int32
    assert arr.indices.dtype == np.int32


def test_sparse_zeros():
    z = sp.zeros("row_sparse", (4, 5))
    assert z.shape == (4, 5) and z.asnumpy().sum() == 0
    z = sp.zeros("csr", (4, 5))
    assert z.shape == (4, 5) and z.asnumpy().sum() == 0
    with pytest.raises(mx.MXNetError):
        sp.zeros("csr", (2, 3, 4))


def test_cast_storage_roundtrip():
    rng = np.random.RandomState(1)
    for shape in [(5, 4), (8, 3)]:
        dn = rng.randn(*shape).astype(np.float32)
        dn[rng.rand(*shape) > 0.5] = 0
        dense = mx.nd.array(dn)
        for stype in ("row_sparse", "csr"):
            s = mx.nd.cast_storage(dense, stype)
            assert s.stype == stype
            assert_almost_equal(s.asnumpy(), dn)
            back = mx.nd.cast_storage(s, "default")
            assert back.stype == "default"
            assert_almost_equal(back.asnumpy(), dn)


def test_csr_slice():
    dense = np.arange(12, dtype=np.float32).reshape(4, 3)
    dense[1] = 0
    arr = sp.cast_storage(mx.nd.array(dense), "csr")
    sl = arr[1:3]
    assert sl.shape == (2, 3)
    assert_almost_equal(sl.asnumpy(), dense[1:3])


def test_sparse_nd_setitem():
    dense, rows = _rsp_fixture()
    dst = sp.zeros("row_sparse", dense.shape)
    dst[:] = sp.row_sparse(dense[rows], rows, dense.shape)
    assert_almost_equal(dst.asnumpy(), dense)
    dst2 = sp.zeros("row_sparse", (3, 3))
    dst2[:] = mx.nd.ones((3, 3))
    assert_almost_equal(dst2.asnumpy(), np.ones((3, 3)))
    with pytest.raises(mx.MXNetError):
        dst2[1:2] = mx.nd.ones((1, 3))


def test_sparse_elemwise_add():
    a_dn = np.zeros((5, 2), np.float32)
    b_dn = np.zeros((5, 2), np.float32)
    a_dn[[0, 3]] = 1.5
    b_dn[[3, 4]] = 2.5
    a = sp.cast_storage(mx.nd.array(a_dn), "row_sparse")
    b = sp.cast_storage(mx.nd.array(b_dn), "row_sparse")
    out = mx.nd.elemwise_add(a, b)
    assert out.stype == "row_sparse"
    assert_almost_equal(out.asnumpy(), a_dn + b_dn)
    # mixed -> dense
    out2 = mx.nd.elemwise_add(a, mx.nd.array(b_dn))
    assert out2.stype == "default"
    assert_almost_equal(out2.asnumpy(), a_dn + b_dn)


def test_sparse_nd_binary_dense_fallback():
    # any dense op works on sparse handles through the dense fallback
    dense, rows = _rsp_fixture()
    arr = sp.row_sparse(dense[rows], rows, dense.shape)
    out = arr * 2 + 1
    assert_almost_equal(out.asnumpy(), dense * 2 + 1)
    neg = -arr
    assert_almost_equal(neg.asnumpy(), -dense)


def test_sparse_dot_csr_dense():
    rng = np.random.RandomState(2)
    lhs_dn = rng.randn(4, 6).astype(np.float32)
    lhs_dn[rng.rand(4, 6) > 0.4] = 0
    rhs = rng.randn(6, 5).astype(np.float32)
    lhs = sp.cast_storage(mx.nd.array(lhs_dn), "csr")
    out = mx.nd.dot(lhs, mx.nd.array(rhs))
    assert_almost_equal(out.asnumpy(), lhs_dn.dot(rhs), rtol=1e-5, atol=1e-5)
    # transpose_a: out[k,:] = sum_i lhs[i,k] rhs[i,:]
    rhs_t = rng.randn(4, 5).astype(np.float32)
    out_t = mx.nd.dot(lhs, mx.nd.array(rhs_t), transpose_a=True)
    assert_almost_equal(out_t.asnumpy(), lhs_dn.T.dot(rhs_t), rtol=1e-5, atol=1e-5)


def test_sparse_dot_csr_vector():
    lhs = sp.csr([1.0, 2.0, 3.0], [0, 2, 3], [0, 2, 1], (2, 3))
    v = mx.nd.array([1.0, 1.0, 1.0])
    out = mx.nd.dot(lhs, v)
    assert out.shape == (2,)
    assert_almost_equal(out.asnumpy(), np.array([3.0, 3.0], np.float32))
    out_t = mx.nd.dot(lhs, mx.nd.array([1.0, 2.0]), transpose_a=True)
    assert out_t.shape == (3,)
    assert_almost_equal(out_t.asnumpy(), lhs.asnumpy().T.dot([1.0, 2.0]))


def test_csr_column_index_validation():
    with pytest.raises(mx.MXNetError):
        sp.csr([1.0], [0, 1], [7], (1, 4))


def test_libsvm_rejects_out_of_range_feature(tmp_path):
    fname = str(tmp_path / "bad.libsvm")
    with open(fname, "w") as f:
        f.write("1 0:1.0 7:9.0\n")
    with pytest.raises(mx.MXNetError):
        mx.io.LibSVMIter(data_libsvm=fname, data_shape=(4,), batch_size=1)


def test_row_sparse_pull_per_device_row_ids():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.array(np.arange(12, dtype=np.float32).reshape(6, 2)))
    a = sp.zeros("row_sparse", (6, 2))
    b = sp.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull(
        "w", out=[a, b], row_ids=[mx.nd.array([0, 1]), mx.nd.array([4, 5])]
    )
    assert_almost_equal(np.asarray(a.indices.asnumpy()), [0, 1])
    assert_almost_equal(np.asarray(b.indices.asnumpy()), [4, 5])
    assert_almost_equal(b.asnumpy()[5], [10, 11])
    assert a.asnumpy()[4:].sum() == 0


def test_sparse_retain():
    dense, rows = _rsp_fixture()
    arr = sp.row_sparse(dense[rows], rows, dense.shape)
    keep = mx.nd.array(np.array([0, 5], np.float32))
    out = mx.nd.sparse_retain(arr, keep)
    expect = np.zeros_like(dense)
    expect[[0, 5]] = dense[[0, 5]]
    assert out.stype == "row_sparse"
    assert_almost_equal(out.asnumpy(), expect)


def test_sparse_sgd_update_matches_dense():
    rng = np.random.RandomState(3)
    w0 = rng.randn(6, 4).astype(np.float32)
    g_dn = np.zeros((6, 4), np.float32)
    g_dn[[1, 4]] = rng.randn(2, 4).astype(np.float32)
    grad = sp.cast_storage(mx.nd.array(g_dn), "row_sparse")

    w_sparse = mx.nd.array(w0)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.01,
                              rescale_grad=0.5)
    state = opt.create_state(0, w_sparse)
    opt.update(0, w_sparse, grad, state)

    w_dense = mx.nd.array(w0)
    opt2 = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9, wd=0.01,
                               rescale_grad=0.5)
    state2 = opt2.create_state(0, w_dense)
    opt2.update(0, w_dense, mx.nd.array(g_dn), state2)

    # rows with gradient must match the dense update exactly
    assert_almost_equal(
        w_sparse.asnumpy()[[1, 4]], w_dense.asnumpy()[[1, 4]], rtol=1e-5, atol=1e-6
    )
    # untouched rows must be untouched (lazy update semantics of the sparse
    # kernel — dense applies wd decay everywhere, sparse only where grads are)
    assert_almost_equal(w_sparse.asnumpy()[[0, 2, 3, 5]], w0[[0, 2, 3, 5]])


def test_sparse_adam_update_matches_dense():
    rng = np.random.RandomState(4)
    w0 = rng.randn(5, 3).astype(np.float32)
    g_dn = np.zeros((5, 3), np.float32)
    g_dn[[0, 2]] = rng.randn(2, 3).astype(np.float32)
    grad = sp.cast_storage(mx.nd.array(g_dn), "row_sparse")

    w_s = mx.nd.array(w0)
    opt = mx.optimizer.create("adam", learning_rate=0.01)
    st = opt.create_state(0, w_s)
    opt.update(0, w_s, grad, st)

    w_d = mx.nd.array(w0)
    opt2 = mx.optimizer.create("adam", learning_rate=0.01)
    st2 = opt2.create_state(0, w_d)
    opt2.update(0, w_d, mx.nd.array(g_dn), st2)

    assert_almost_equal(w_s.asnumpy()[[0, 2]], w_d.asnumpy()[[0, 2]],
                        rtol=1e-5, atol=1e-6)
    assert_almost_equal(w_s.asnumpy()[[1, 3, 4]], w0[[1, 3, 4]])


def test_sparse_kvstore_push_pull():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((6, 2)))
    g1 = sp.row_sparse(np.ones((2, 2), np.float32), [0, 3], (6, 2))
    g2 = sp.row_sparse(np.ones((2, 2), np.float32) * 2, [3, 5], (6, 2))
    kv.push("w", [g1, g2])
    out = mx.nd.zeros((6, 2))
    kv.pull("w", out=out)
    expect = np.zeros((6, 2), np.float32)
    expect[0] = 1
    expect[3] = 3
    expect[5] = 2
    assert_almost_equal(out.asnumpy(), expect)

    # row_sparse_pull fetches only requested rows
    dst = sp.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("w", out=dst, row_ids=mx.nd.array([3, 5]))
    got = dst.asnumpy()
    assert_almost_equal(got[[3, 5]], expect[[3, 5]])
    assert got[[0, 1, 2, 4]].sum() == 0


def test_sparse_pickle_save_load(tmp_path):
    arr = rand_ndarray((6, 4), "row_sparse")
    blob = pickle.dumps(arr)
    back = pickle.loads(blob)
    assert back.stype == "row_sparse"
    assert_almost_equal(back.asnumpy(), arr.asnumpy())

    fname = str(tmp_path / "sparse.params")
    csr_arr = rand_ndarray((5, 7), "csr")
    mx.nd.save(fname, {"rsp": arr, "csr": csr_arr, "dn": mx.nd.ones((2, 2))})
    loaded = mx.nd.load(fname)
    assert loaded["rsp"].stype == "row_sparse"
    assert loaded["csr"].stype == "csr"
    assert loaded["dn"].stype == "default"
    assert_almost_equal(loaded["rsp"].asnumpy(), arr.asnumpy())
    assert_almost_equal(loaded["csr"].asnumpy(), csr_arr.asnumpy())


def test_libsvm_iter(tmp_path):
    fname = str(tmp_path / "data.libsvm")
    with open(fname, "w") as f:
        f.write("1 0:1.5 3:2.5\n")
        f.write("0 1:0.5\n")
        f.write("1 2:1.0 3:3.0\n")
        f.write("0 0:4.0\n")
    it = mx.io.LibSVMIter(data_libsvm=fname, data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    b0 = batches[0]
    assert b0.data[0].stype == "csr"
    assert b0.data[0].shape == (2, 4)
    expect0 = np.array([[1.5, 0, 0, 2.5], [0, 0.5, 0, 0]], np.float32)
    assert_almost_equal(b0.data[0].asnumpy(), expect0)
    assert_almost_equal(b0.label[0].asnumpy(), np.array([1, 0], np.float32))
    it.reset()
    again = list(it)
    assert_almost_equal(again[0].data[0].asnumpy(), expect0)


def test_libsvm_iter_pads_partial_batch(tmp_path):
    fname = str(tmp_path / "small.libsvm")
    with open(fname, "w") as f:
        f.write("1 0:1.0\n")
        f.write("0 2:2.0\n")
        f.write("1 1:3.0\n")
    it = mx.io.LibSVMIter(data_libsvm=fname, data_shape=(3,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].pad == 0
    assert batches[1].pad == 1
    got = batches[1].data[0].asnumpy()
    assert_almost_equal(got[0], np.array([0, 3.0, 0], np.float32))
    assert got[1].sum() == 0  # padded row is all-zero
    # dataset smaller than batch_size still yields one (padded) batch
    it2 = mx.io.LibSVMIter(data_libsvm=fname, data_shape=(3,), batch_size=8)
    b = list(it2)
    assert len(b) == 1 and b[0].pad == 5


def test_sparse_embedding_grad_pattern():
    """Embedding-style workload: dense grad -> row_sparse -> sparse update.

    The reference's sparse embedding test checks that only looked-up rows
    change (test_sparse_operator.py:135); here the tape produces a dense
    grad and cast_storage recovers the row-sparse structure for the update.
    """
    vocab, dim = 8, 3
    rng = np.random.RandomState(5)
    w0 = rng.randn(vocab, dim).astype(np.float32)
    idx = np.array([1, 1, 6], np.float32)

    data = mx.sym.Variable("data")
    weight = mx.sym.Variable("embed_weight")
    embed = mx.sym.Embedding(data=data, weight=weight, input_dim=vocab,
                             output_dim=dim, name="embed")
    loss = mx.sym.make_loss(mx.sym.sum(embed))
    exe = loss.simple_bind(mx.cpu(), data=(3,), grad_req={"embed_weight": "write"})
    exe.arg_dict["data"][:] = mx.nd.array(idx)
    exe.arg_dict["embed_weight"][:] = mx.nd.array(w0)
    exe.forward(is_train=True)
    exe.backward()
    g = exe.grad_dict["embed_weight"]
    g_rsp = mx.nd.cast_storage(g, "row_sparse")
    touched = set(g_rsp.indices.asnumpy().astype(int).tolist())
    assert touched == {1, 6}

    w = mx.nd.array(w0)
    opt = mx.optimizer.create("sgd", learning_rate=1.0)
    opt.update(0, w, g_rsp, None)
    out = w.asnumpy()
    expect = w0.copy()
    expect[1] -= 2.0  # index 1 looked up twice, d(sum)/d(row) = count
    expect[6] -= 1.0
    assert_almost_equal(out, expect, rtol=1e-5, atol=1e-6)


def test_setitem_after_densify_clears_cache():
    """Regression: '[:] = dense' must invalidate the cached dense buffer
    created by an earlier todense()/asnumpy() read."""
    a = sp.row_sparse(np.ones((1, 3), np.float32), np.array([0], np.int32),
                      (2, 3))
    _ = a.asnumpy()                     # populate the dense cache
    new = np.eye(2, 3, dtype=np.float32)
    a[:] = new
    assert_almost_equal(a.todense().asnumpy(), new)
    assert_almost_equal(a.asnumpy(), new)
    # same through the NDArray branch
    _ = a.asnumpy()
    a[:] = mx.nd.zeros((2, 3))
    assert_almost_equal(a.asnumpy(), np.zeros((2, 3), np.float32))
