"""Test configuration: force the CPU backend with 8 virtual devices.

The axon site config pins JAX_PLATFORMS=axon (one real TPU chip); unit tests
run on XLA:CPU with an 8-device virtual mesh so multi-chip semantics are
testable without hardware (SURVEY.md §4 implication). Must happen before the
jax backend initialises.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# unit tests must not read (or populate) a developer's warm executable
# cache — subprocess cache-contract tests opt back in with their own dir
os.environ.pop("MXNET_AOT_CACHE", None)
# The bench deployment's sitecustomize dials the single-chip axon tunnel
# in EVERY interpreter at boot when the axon pool vars are set. The pytest
# process holds the chip session, so any spawned child that initialises
# jax — compiled C/C++ clients with embedded CPython included — spins in
# the chip-claim retry loop until its timeout (the 300 s hang mode,
# VERDICT r5). Scrub the axon boot vars HERE, once: every test builds its
# subprocess env from os.environ (or inherits it), so all spawn sites get
# a clean environment instead of each repeating the pop.
for _k in [k for k in os.environ if k.startswith("PALLAS_AXON_")]:
    os.environ.pop(_k, None)

import jax
import pytest

jax.config.update("jax_platforms", "cpu")


_DIST_PROBE = None  # None = not probed yet; True/False = cached verdict


def _dist_collectives_supported():
    """Probe (once per session): can this backend execute a CROSS-PROCESS
    collective? XLA:CPU cannot ("Multiprocess computations aren't
    implemented on the CPU backend") — the 8-device virtual mesh above is
    single-process only. Spawn a real 2-rank dist_sync allreduce through
    tools/launch.py (the exact op the dist tests exercise) and see if it
    completes; TPU/GPU pods pass, CPU-only hosts skip."""
    global _DIST_PROBE
    if _DIST_PROBE is not None:
        return _DIST_PROBE
    import socket
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    worker = (
        "import os; os.environ['JAX_PLATFORMS'] = "
        "os.environ.get('JAX_PLATFORMS', 'cpu');"
        "import mxnet_tpu as mx;"
        "kv = mx.kv.create('dist_sync');"
        "a = mx.nd.ones((2,)); kv.init(0, a); kv.push(0, a);"
        "out = mx.nd.zeros((2,)); kv.pull(0, out=out);"
        "print('DIST-PROBE OK', float(out.asnumpy().sum()), flush=True)"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # ranks get their own un-virtualized jax
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, os.path.join(root, "tools", "launch.py"),
           "-n", "2", "--launcher", "local", "--port", str(port),
           sys.executable, "-c", worker]
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=120)
        _DIST_PROBE = (proc.returncode == 0
                       and proc.stdout.count("DIST-PROBE OK") >= 2)
    except (subprocess.TimeoutExpired, OSError):
        _DIST_PROBE = False
    return _DIST_PROBE


def pytest_collection_modifyitems(config, items):
    """Skip capability-gated tests on backends missing the capability:
    @pytest.mark.aot_serialization when compiled executables cannot
    serialize (probed via mxnet_tpu.aot), @pytest.mark.dist_multiprocess
    when cross-process collectives cannot execute (probed via a 2-rank
    launch)."""
    import pytest

    marked = [item for item in items
              if "aot_serialization" in item.keywords]
    if marked:
        from mxnet_tpu import aot

        if not aot.supports_serialization():
            skip = pytest.mark.skip(
                reason="backend cannot serialize compiled executables")
            for item in marked:
                item.add_marker(skip)

    dist_marked = [item for item in items
                   if "dist_multiprocess" in item.keywords]
    if dist_marked and not _dist_collectives_supported():
        skip = pytest.mark.skip(
            reason="backend cannot execute multiprocess collectives "
                   "(XLA:CPU); probed via a 2-rank dist_sync allreduce")
        for item in dist_marked:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _sanitize_marked(request):
    """Run `sanitize`-marked tests under the runtime lock-order sanitizer
    (mxnet_tpu.analysis.sanitizer): threading.Lock/RLock are swapped for
    instrumented wrappers for the duration of the test, and any ABBA
    cycle observed in the process-wide lock-order graph fails the test
    with both acquisition stacks. Opt out with MXNET_SANITIZER=0 (the
    tier-1 default is ON for marked suites)."""
    if request.node.get_closest_marker("sanitize") is None \
            or os.environ.get("MXNET_SANITIZER", "1") == "0":
        yield
        return

    from mxnet_tpu.analysis import sanitizer

    sanitizer.install()
    sanitizer.reset()
    try:
        yield
    finally:
        rep = sanitizer.report()
        sanitizer.uninstall()
        sanitizer.reset()
    if rep["cycles"]:
        pytest.fail("runtime sanitizer observed lock-order cycle(s):\n"
                    + sanitizer.format_report(rep), pytrace=False)
