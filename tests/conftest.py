"""Test configuration: force the CPU backend with 8 virtual devices.

The axon site config pins JAX_PLATFORMS=axon (one real TPU chip); unit tests
run on XLA:CPU with an 8-device virtual mesh so multi-chip semantics are
testable without hardware (SURVEY.md §4 implication). Must happen before the
jax backend initialises.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
# unit tests must not read (or populate) a developer's warm executable
# cache — subprocess cache-contract tests opt back in with their own dir
os.environ.pop("MXNET_AOT_CACHE", None)
# The bench deployment's sitecustomize dials the single-chip axon tunnel
# in EVERY interpreter at boot when the axon pool vars are set. The pytest
# process holds the chip session, so any spawned child that initialises
# jax — compiled C/C++ clients with embedded CPython included — spins in
# the chip-claim retry loop until its timeout (the 300 s hang mode,
# VERDICT r5). Scrub the axon boot vars HERE, once: every test builds its
# subprocess env from os.environ (or inherits it), so all spawn sites get
# a clean environment instead of each repeating the pop.
for _k in [k for k in os.environ if k.startswith("PALLAS_AXON_")]:
    os.environ.pop(_k, None)

import jax

jax.config.update("jax_platforms", "cpu")


def pytest_collection_modifyitems(config, items):
    """Skip @pytest.mark.aot_serialization tests on backends that cannot
    serialize compiled executables (probed once, mxnet_tpu.aot)."""
    import pytest

    marked = [item for item in items
              if "aot_serialization" in item.keywords]
    if not marked:
        return
    from mxnet_tpu import aot

    if not aot.supports_serialization():
        skip = pytest.mark.skip(
            reason="backend cannot serialize compiled executables")
        for item in marked:
            item.add_marker(skip)
