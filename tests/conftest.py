"""Test configuration: force the CPU backend with 8 virtual devices.

The axon site config pins JAX_PLATFORMS=axon (one real TPU chip); unit tests
run on XLA:CPU with an 8-device virtual mesh so multi-chip semantics are
testable without hardware (SURVEY.md §4 implication). Must happen before the
jax backend initialises.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
