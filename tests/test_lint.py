"""graftlint: the tier-1 gate (zero non-baselined findings on the tree)
plus the analyzer's own contract tests — every checker proves it fires on
a seeded violation and stays quiet on the clean counterpart, pragmas
suppress (and malformed pragmas are themselves findings), and the
baseline round-trips deterministically.
"""

import ast
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu import analysis
from mxnet_tpu.analysis.checkers.host_sync import ROOTS

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "data", "lint_fixtures")
BASELINE = os.path.join(ROOT, "tools", "lint_baseline.json")

CHECKERS = [c.name for c in analysis.all_checkers()]
_FIXTURE_NAME = {  # checker name -> fixture stem
    "host-sync": "host_sync",
    "trace-purity": "trace_purity",
    "env-registry": "env_registry",
    "telemetry-catalog": "telemetry_catalog",
    "lock-discipline": "lock_discipline",
    "exception-swallow": "exception_swallow",
    "typos": "typos",
}


def _lint(files, baseline=None, checks=None):
    return analysis.run_suite(ROOT, files=files, baseline=baseline,
                              checks=checks)


def _fixture(stem, flavor):
    path = os.path.join(FIXTURES, f"{stem}_{flavor}.py")
    assert os.path.exists(path), f"missing fixture {path}"
    return path


# --------------------------------------------------------------------------
# the gate: the live tree carries zero non-baselined findings
# --------------------------------------------------------------------------

def test_tree_has_zero_new_findings():
    result = analysis.run_suite(
        ROOT, baseline=analysis.load_baseline(BASELINE))
    rendered = "\n".join(f.render() for f in result.findings)
    assert not result.findings, (
        f"graftlint found {len(result.findings)} new finding(s) — fix "
        "them, add a pragma with a reason, or (last resort) regenerate "
        f"the baseline:\n{rendered}"
    )


def test_baseline_entries_still_hit():
    """A baseline entry whose finding was fixed must be removed — a stale
    baseline could silently absorb a NEW finding with the same key."""
    result = analysis.run_suite(
        ROOT, baseline=analysis.load_baseline(BASELINE))
    assert not result.stale_baseline, (
        "stale baseline entries (fixed findings still grandfathered): "
        f"{result.stale_baseline} — run tools/lint.py --write-baseline"
    )


def test_hot_roots_table_matches_tree():
    """Every declared hot ROOT qualname must resolve to a real function —
    otherwise a rename silently removes an entire hot plane from
    reachability coverage (the failure mode that killed the old
    HOT_PATHS table, except N functions at a time)."""
    from mxnet_tpu.analysis.core import iter_defs

    for rel, quals in ROOTS.items():
        full = os.path.join(ROOT, rel)
        with open(full, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=rel)
        present = {q for q, _cls, _fn in iter_defs(tree)}
        missing = set(quals) - present
        assert not missing, (
            f"{rel}: declared hot roots not found: {sorted(missing)} "
            "(renamed? update ROOTS in analysis/checkers/host_sync.py)"
        )


# --------------------------------------------------------------------------
# per-checker fixtures: seeded violation fires, clean counterpart passes
# --------------------------------------------------------------------------

@pytest.mark.parametrize("check", CHECKERS)
def test_checker_fires_on_seeded_violation(check):
    bad = _fixture(_FIXTURE_NAME[check], "bad")
    result = _lint([bad], checks=[check])
    hits = [f for f in result.findings if f.check == check]
    assert hits, f"{check} did not fire on its seeded violation fixture"
    for f in hits:
        assert f.path.endswith(f"{_FIXTURE_NAME[check]}_bad.py")
        assert f.line > 0 and f.message


@pytest.mark.parametrize("check", CHECKERS)
def test_checker_passes_clean_fixture(check):
    clean = _fixture(_FIXTURE_NAME[check], "clean")
    result = _lint([clean])  # ALL checkers: clean means clean
    rendered = "\n".join(f.render() for f in result.findings)
    assert not result.findings, (
        f"clean fixture for {check} produced findings:\n{rendered}")


def test_trace_purity_catches_each_impurity_kind():
    bad = _fixture("trace_purity", "bad")
    result = _lint([bad], checks=["trace-purity"])
    messages = " | ".join(f.message for f in result.findings)
    for needle in ("wall-clock", "RNG", "trace time", "closed-over"):
        assert needle in messages, (
            f"expected a {needle!r} finding in: {messages}")


def test_lock_discipline_catches_each_rule():
    bad = _fixture("lock_discipline", "bad")
    result = _lint([bad], checks=["lock-discipline"])
    messages = " | ".join(f.message for f in result.findings)
    for needle in ("cycle", "written", "run lock", "hand-off lock"):
        assert needle in messages, (
            f"expected a {needle!r} finding in: {messages}")


def test_lock_discipline_is_interprocedural():
    """The acceptance pins of the call-graph upgrade: an ABBA cycle whose
    two halves live in different classes and only meet through call
    edges, and a blocking wait hidden one call below the lock."""
    bad = _fixture("lock_discipline", "bad")
    result = _lint([bad], checks=["lock-discipline"])
    messages = " | ".join(f.message for f in result.findings)
    # cross-class cycle: both lock ids named, from different classes
    assert "Journal._log_lock" in messages
    assert "StatSink._stat_lock" in messages
    cycle_msgs = [f.message for f in result.findings
                  if "cycle" in f.message]
    assert any("Journal._log_lock" in m and "StatSink._stat_lock" in m
               for m in cycle_msgs), cycle_msgs
    # blocking Event.wait reported at the call site, naming the callee
    assert "inside" in messages and "_wait_ready" in messages


def test_host_sync_reports_two_hop_chain():
    """A sync two call hops below a hot root is found, and the finding's
    message carries the root→function chain."""
    bad = _fixture("host_sync", "bad")
    result = _lint([bad], checks=["host-sync"])
    two_hop = [f for f in result.findings if f.context == "fetch_metrics"]
    assert two_hop, [f.render() for f in result.findings]
    msg = two_hop[0].message
    assert "reachable from hot root" in msg
    assert "`pump`" not in msg  # chains are fully qualified…
    assert "pump" in msg and "step" in msg and "->" in msg


def test_io_plane_is_in_scope():
    """io_plane.py must be covered by BOTH interprocedural checkers —
    the workers/events/watchdogs that shipped unanalyzed under the PR-8
    scope tables are the motivating case for tree-wide analysis."""
    from mxnet_tpu.analysis.checkers.lock_discipline import (
        LockDisciplineChecker)

    assert "mxnet_tpu/io_plane.py" in ROOTS
    ctx = analysis.build_context(
        ROOT, files=[os.path.join(ROOT, "mxnet_tpu", "io_plane.py")])
    probe = LockDisciplineChecker()
    probe.classes, probe.attr_owner = {}, {}
    probe.mod_prims, probe.kinds = {}, {}
    for unit in ctx.units:
        if unit.tree is not None:
            probe._discover(unit)
    prims = {info.prim_id(a) for info in probe.classes.values()
             for a in info.prims}
    assert "DecodePool._cv" in prims, prims


# --------------------------------------------------------------------------
# pragmas
# --------------------------------------------------------------------------

def test_pragma_suppresses_file_and_line_scoped():
    path = os.path.join(FIXTURES, "pragma_suppressed.py")
    result = _lint([path])
    assert not result.findings, (
        "pragma-carrying fixture still reports: "
        + "; ".join(f.render() for f in result.findings))
    suppressed = {f.check for f in result.suppressed}
    assert {"typos", "env-registry"} <= suppressed


def test_malformed_pragma_is_itself_a_finding():
    path = os.path.join(FIXTURES, "pragma_malformed.py")
    result = _lint([path])
    pragma_findings = [f for f in result.findings if f.check == "pragma"]
    assert len(pragma_findings) == 2
    messages = " | ".join(f.message for f in pragma_findings)
    assert "no reason" in messages
    assert "unknown check" in messages
    # and the underlying env-registry findings are NOT suppressed
    assert any(f.check == "env-registry" for f in result.findings)


def test_pragma_quoted_in_docstring_is_inert():
    src = '"""Docs may quote `# graftlint: allow=typos(reason)`."""\n'
    tmp = os.path.join(FIXTURES, "..", "_tmp_docstring.py")
    tmp = os.path.abspath(tmp)
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(src + "interals = 1\n")
        result = _lint([tmp])
        assert any(f.check == "typos" for f in result.findings), (
            "docstring-quoted pragma must not suppress anything")
        assert not any(f.check == "pragma" for f in result.findings)
    finally:
        os.unlink(tmp)


# --------------------------------------------------------------------------
# baseline round-trip
# --------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    bad = _fixture("typos", "bad")
    first = _lint([bad])
    assert first.findings
    bl_path = str(tmp_path / "baseline.json")
    analysis.write_baseline(first.findings, bl_path)

    second = _lint([bad], baseline=analysis.load_baseline(bl_path))
    assert not second.findings, "baselined findings reported as new"
    assert len(second.baselined) == len(first.findings)
    assert not second.stale_baseline

    # fixing one finding makes its baseline entry stale (reported)
    clean = _fixture("typos", "clean")
    third = _lint([clean], baseline=analysis.load_baseline(bl_path))
    assert not third.findings
    assert third.stale_baseline


def test_baseline_is_deterministic(tmp_path):
    bad = _fixture("lock_discipline", "bad")
    findings = _lint([bad]).findings
    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    analysis.write_baseline(findings, a)
    analysis.write_baseline(list(reversed(findings)), b)
    with open(a, "rb") as fa, open(b, "rb") as fb:
        assert fa.read() == fb.read(), "baseline bytes depend on order"
    data = json.load(open(a))
    for entry in data["findings"]:
        assert "line" not in entry, "baseline must be line-number free"
        assert not os.path.isabs(entry["path"])


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _run_cli(args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lint.py")] + args,
        capture_output=True, text=True, cwd=ROOT, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


def test_cli_exit_codes_and_json():
    bad = _fixture("typos", "bad")
    proc = _run_cli([bad, "--format=json", "--no-baseline"])
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] and all(
        f["check"] == "typos" for f in report["findings"])

    clean = _fixture("typos", "clean")
    proc = _run_cli([clean, "--format=json", "--no-baseline"])
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["findings"] == []


def test_cli_tree_is_green():
    """The committed tree + committed baseline must satisfy the CLI the
    way CI invokes it (this is the per-PR gate's exact spelling)."""
    proc = _run_cli([])
    assert proc.returncode == 0, (
        f"python tools/lint.py failed:\n{proc.stdout}\n{proc.stderr}")


def test_cli_only_flag_restricts_checkers():
    """`--only=` is the triage spelling of `--checks`: the bad lock
    fixture fires under its own checker and goes green when the run is
    restricted to an unrelated one."""
    bad = _fixture("lock_discipline", "bad")
    proc = _run_cli([bad, "--only=lock-discipline", "--format=json",
                     "--no-baseline"])
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    assert report["findings"] and all(
        f["check"] == "lock-discipline" for f in report["findings"])

    proc = _run_cli([bad, "--only=exception-swallow", "--format=json",
                     "--no-baseline"])
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["findings"] == []


def test_cli_callgraph_mode():
    """`--callgraph QUALNAME` prints the node's callers/callees plus the
    graph totals; an unknown name exits 2."""
    proc = _run_cli(["--callgraph", "DecodePool.next_result"])
    assert proc.returncode == 0, proc.stderr
    for needle in ("DecodePool.next_result", "callees", "callers",
                   "graph:", "functions"):
        assert needle in proc.stdout, proc.stdout

    proc = _run_cli(["--callgraph", "NoSuchFunctionAnywhere"])
    assert proc.returncode == 2, proc.stdout


def test_cli_does_not_import_the_framework():
    """Linting must work without jax: the CLI loads the self-contained
    analysis package, never mxnet_tpu itself (a broken venv must still
    be able to lint). The call-graph engine and the runtime sanitizer
    ride the same standalone load path."""
    lint_py = os.path.join(ROOT, "tools", "lint.py")
    probe = (
        "import sys, runpy\n"
        "sys.argv = ['lint.py', '--list']\n"
        "runpy.run_path(r'%s', run_name='__main__')\n"
    ) % lint_py
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         "sys.modules['jax'] = None  # any jax import now explodes\n"
         + probe],
        capture_output=True, text=True, cwd=ROOT, timeout=120)
    assert "host-sync" in proc.stdout, proc.stderr

    # the whole-program call graph builds with jax absent too
    probe = (
        "import sys, runpy\n"
        "sys.argv = ['lint.py', '--callgraph', 'DecodePool.next_result']\n"
        "runpy.run_path(r'%s', run_name='__main__')\n"
    ) % lint_py
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys\nsys.modules['jax'] = None\n" + probe],
        capture_output=True, text=True, cwd=ROOT, timeout=120)
    assert "callees" in proc.stdout, proc.stderr

    # the sanitizer arms standalone: lock factories patch, a guarded
    # acquire/release round-trips, and the report comes back clean
    san = os.path.join(ROOT, "mxnet_tpu", "analysis", "sanitizer.py")
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys, importlib.util\n"
         "sys.modules['jax'] = None\n"
         "spec = importlib.util.spec_from_file_location("
         "'sanitizer', r'%s')\n"
         "san = importlib.util.module_from_spec(spec)\n"
         "spec.loader.exec_module(san)\n"
         "san.install()\n"
         "import threading\n"
         "with threading.Lock():\n"
         "    pass\n"
         "rep = san.report()\n"
         "san.uninstall()\n"
         "assert rep['cycles'] == [], rep\n"
         "print('sanitizer-standalone-ok')\n" % san],
        capture_output=True, text=True, cwd=ROOT, timeout=120)
    assert "sanitizer-standalone-ok" in proc.stdout, (
        proc.stdout + proc.stderr)
