"""Caffe prototxt -> Symbol converter (tools/caffe_converter.py; the
reference tools/caffe_converter/convert_symbol.py analogue)."""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

from caffe_converter import convert_symbol, parse_prototxt  # noqa: E402

_LENET_PROTOTXT = """
name: "LeNet"
input: "data"
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 20 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "conv2"
  type: "Convolution"
  bottom: "pool1"
  top: "conv2"
  convolution_param { num_output: 50 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool2"
  type: "Pooling"
  bottom: "conv2"
  top: "pool2"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool2"
  top: "ip1"
  inner_product_param { num_output: 500 }
}
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer {
  name: "ip2"
  type: "InnerProduct"
  bottom: "ip1"
  top: "ip2"
  inner_product_param { num_output: 10 }
}
layer {
  name: "loss"
  type: "SoftmaxWithLoss"
  bottom: "ip2"
  bottom: "label"
  top: "loss"
}
"""


def test_parse_prototxt_structure():
    net = parse_prototxt(_LENET_PROTOTXT)
    assert net["name"] == "LeNet"
    layers = net["layer"]
    assert len(layers) == 8
    assert layers[0]["convolution_param"]["num_output"] == 20
    assert layers[1]["pooling_param"]["pool"] == "MAX"
    assert layers[-1]["bottom"] == ["ip2", "label"]


def test_convert_lenet_trains():
    sym, input_name = convert_symbol(_LENET_PROTOTXT)
    assert input_name == "data"
    args = sym.list_arguments()
    assert "conv1_weight" in args and "ip2_bias" in args

    # converted LeNet must train end to end on synthetic digits.
    # Initializer + iterator shuffle draw from global RNG streams, so pin
    # them — convergence on this budget is seed-marginal otherwise.
    mx.random.seed(0)
    np.random.seed(0)
    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, 128).astype(np.float32)
    # separable by mean brightness: class c images sit at intensity c/10
    x = (rng.rand(128, 1, 28, 28) * 0.1
         + y[:, None, None, None] / 10.0).astype(np.float32)
    it = mx.io.NDArrayIter(x, {"label": y}, batch_size=32, shuffle=True)
    mod = mx.mod.Module(sym, context=mx.cpu(), data_names=("data",),
                        label_names=("label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.005})
    metric = mx.metric.Accuracy()
    for epoch in range(40):
        it.reset()
        metric.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
            mod.update_metric(metric, b.label)
    assert metric.get()[1] > 0.8, metric.get()


def test_convert_vgg_style_blocks_and_eltwise():
    proto = """
    input: "data"
    layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1 } }
    layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
    layer { name: "c2" type: "Convolution" bottom: "c1" top: "c2"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1 } }
    layer { name: "sum" type: "Eltwise" bottom: "c1" bottom: "c2" top: "sum" }
    layer { name: "gp" type: "Pooling" bottom: "sum" top: "gp"
            pooling_param { pool: AVE global_pooling: true } }
    layer { name: "fc" type: "InnerProduct" bottom: "gp" top: "fc"
            inner_product_param { num_output: 4 } }
    layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
    """
    sym, _ = convert_symbol(proto)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(2, 3, 16, 16))
    rng = np.random.RandomState(1)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = rng.uniform(-0.1, 0.1, a.shape).astype(np.float32)
    exe.arg_dict["data"][:] = rng.rand(2, 3, 16, 16).astype(np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 4)
    assert np.allclose(out.sum(1), 1.0, atol=1e-4)


def test_convert_training_prototxt_with_data_layer_and_bn():
    """Real-world shapes: a Data layer with data AND label tops, lowercase
    boolean tokens, BatchNorm+Scale pairs, and Eltwise coeffs."""
    proto = """
    layer { name: "mnist" type: "Data" top: "data" top: "label" }
    layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1
                                bias_term: false } }
    layer { name: "bn1" type: "BatchNorm" bottom: "c1" top: "c1" }
    layer { name: "sc1" type: "Scale" bottom: "c1" top: "c1" }
    layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
    layer { name: "c2" type: "Convolution" bottom: "c1" top: "c2"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1 } }
    layer { name: "diff" type: "Eltwise" bottom: "c1" bottom: "c2" top: "diff"
            eltwise_param { operation: SUM coeff: 1 coeff: -1 } }
    layer { name: "gp" type: "Pooling" bottom: "diff" top: "gp"
            pooling_param { pool: AVE global_pooling: true } }
    layer { name: "fc" type: "InnerProduct" bottom: "gp" top: "fc"
            inner_product_param { num_output: 3 } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc"
            bottom: "label" top: "loss" }
    """
    sym, input_name = convert_symbol(proto)
    assert input_name == "data"
    args = sym.list_arguments()
    assert "label" in args          # the Data layer's second top
    assert "c1_weight" in args and "c1_bias" not in args  # bias_term false
    assert "bn1_gamma" in args      # learnable (Scale folded, fix_gamma off)
    exe = sym.simple_bind(mx.cpu(), data=(2, 3, 8, 8), label=(2,))
    rng = np.random.RandomState(0)
    for n, a in exe.arg_dict.items():
        if n not in ("data", "label"):
            a[:] = rng.uniform(-0.2, 0.2, a.shape).astype(np.float32)
    exe.arg_dict["data"][:] = rng.rand(2, 3, 8, 8).astype(np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 3) and np.allclose(out.sum(1), 1, atol=1e-4)

    # standalone Scale refuses loudly
    with pytest.raises(ValueError):
        convert_symbol("""
        input: "data"
        layer { name: "s" type: "Scale" bottom: "data" top: "s" }
        """)


# ---------------------------------------------------------------------------
# .caffemodel weights conversion (binary protobuf, no caffe/protoc)
# ---------------------------------------------------------------------------
def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(field, payload):
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _blob_bytes(arr):
    arr = np.asarray(arr, np.float32)
    shape_msg = _ld(1, b"".join(_varint(d) for d in arr.shape))
    return _ld(7, shape_msg) + _ld(5, arr.astype("<f4").tobytes())


def _layer_bytes(name, blobs, legacy=False):
    name_field, blob_field = (4, 6) if legacy else (1, 7)
    body = _ld(name_field, name.encode())
    for b in blobs:
        body += _ld(blob_field, _blob_bytes(b))
    return body


def _caffemodel_bytes(layers, legacy=False):
    net_field = 2 if legacy else 100
    return b"".join(_ld(net_field, _layer_bytes(n, bl, legacy))
                    for n, bl in layers)


_WEIGHTS_PROTOTXT = """
input: "data"
layer { name: "conv" type: "Convolution" bottom: "data" top: "conv"
        convolution_param { num_output: 2 kernel_size: 3 } }
layer { name: "bn" type: "BatchNorm" bottom: "conv" top: "conv"
        batch_norm_param { eps: 1e-5 use_global_stats: true } }
layer { name: "sc" type: "Scale" bottom: "conv" top: "conv" }
layer { name: "relu" type: "ReLU" bottom: "conv" top: "conv" }
layer { name: "fc" type: "InnerProduct" bottom: "conv" top: "fc"
        inner_product_param { num_output: 3 } }
layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
"""


def _weights_fixture(rs):
    conv_w = rs.uniform(-0.5, 0.5, (2, 1, 3, 3)).astype(np.float32)
    conv_b = rs.uniform(-0.1, 0.1, 2).astype(np.float32)
    bn_mean = np.array([0.3, -0.2], np.float32)
    bn_var = np.array([0.9, 1.4], np.float32)
    scale_factor = np.array([2.0], np.float32)  # stats stored pre-scaled
    gamma = np.array([1.5, 0.7], np.float32)
    beta = np.array([0.1, -0.3], np.float32)
    fc_w = rs.uniform(-0.4, 0.4, (3, 2 * 4 * 4)).astype(np.float32)
    fc_b = rs.uniform(-0.1, 0.1, 3).astype(np.float32)
    layers = [
        ("conv", [conv_w, conv_b]),
        ("bn", [bn_mean * 2.0, bn_var * 2.0, scale_factor]),
        ("sc", [gamma, beta]),
        ("fc", [fc_w, fc_b]),
    ]
    return layers, (conv_w, conv_b, bn_mean, bn_var, gamma, beta, fc_w, fc_b)


def _numpy_oracle(x, parts):
    """Hand-computed forward of the fixture net (valid 3x3 conv, BN with
    global stats, ReLU, FC, softmax)."""
    conv_w, conv_b, bn_mean, bn_var, gamma, beta, fc_w, fc_b = parts
    n, _, h, w = x.shape
    oh, ow = h - 2, w - 2
    conv = np.zeros((n, 2, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i:i + 3, j:j + 3]          # (n,1,3,3)
            conv[:, :, i, j] = np.einsum(
                "ncij,ocij->no", patch, conv_w) + conv_b
    bn = (conv - bn_mean[None, :, None, None]) / np.sqrt(
        bn_var[None, :, None, None] + 1e-5)
    bn = bn * gamma[None, :, None, None] + beta[None, :, None, None]
    act = np.maximum(bn, 0)
    flat = act.reshape(n, -1)
    logits = flat @ fc_w.T + fc_b
    e = np.exp(logits - logits.max(1, keepdims=True))
    return e / e.sum(1, keepdims=True)


@pytest.mark.parametrize("legacy", [False, True])
def test_caffemodel_weights_convert_and_match_oracle(legacy):
    from caffe_converter import convert_model

    rs = np.random.RandomState(42)
    layers, parts = _weights_fixture(rs)
    model = _caffemodel_bytes(layers, legacy=legacy)
    sym, arg_params, aux_params, input_name = convert_model(
        _WEIGHTS_PROTOTXT, model)
    assert input_name == "data"
    # BN statistics de-scaled by the running scale factor
    np.testing.assert_allclose(
        aux_params["bn_moving_mean"].asnumpy(), parts[2], rtol=1e-6)
    np.testing.assert_allclose(
        aux_params["bn_moving_var"].asnumpy(), parts[3], rtol=1e-6)
    # Scale layer's gamma/beta landed in the folded BatchNorm
    np.testing.assert_allclose(arg_params["bn_gamma"].asnumpy(), parts[4])
    np.testing.assert_allclose(arg_params["bn_beta"].asnumpy(), parts[5])

    x = rs.uniform(-1, 1, (2, 1, 6, 6)).astype(np.float32)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=x.shape)
    exe.copy_params_from(arg_params, aux_params)
    exe.arg_dict["data"][:] = x
    out = exe.forward(is_train=False)[0].asnumpy()
    expect = _numpy_oracle(x, parts)
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_convert_new_layers_deconv_crop_slice_power():
    proto = """
    input: "data"
    layer { name: "dc" type: "Deconvolution" bottom: "data" top: "dc"
            convolution_param { num_output: 2 kernel_size: 2 stride: 2 } }
    layer { name: "crop" type: "Crop" bottom: "dc" bottom: "data" top: "cr"
            crop_param { axis: 2 offset: 0 } }
    layer { name: "sl" type: "Slice" bottom: "cr" top: "s1" top: "s2"
            slice_param { axis: 1 } }
    layer { name: "pw" type: "Power" bottom: "s1" top: "pw"
            power_param { power: 2 scale: 0.5 shift: 1 } }
    """
    from caffe_converter import convert_symbol as cs

    sym, _ = cs(proto)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(1, 2, 8, 8))
    rng = np.random.RandomState(0)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = rng.uniform(-0.2, 0.2, a.shape).astype(np.float32)
    x = rng.rand(1, 2, 8, 8).astype(np.float32)
    exe.arg_dict["data"][:] = x
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (1, 1, 8, 8)
    # Power semantics: (shift + scale*x)^power on the first slice half
    assert np.all(out >= 0)


def test_repeated_fields_and_required_errors():
    # repeated kernel_size entries are (h, w) per caffe semantics
    proto = """
    input: "data"
    layer { name: "c" type: "Convolution" bottom: "data" top: "c"
            convolution_param { num_output: 4 kernel_size: 3 kernel_size: 5
                                pad: 1 pad: 2 } }
    """
    from caffe_converter import convert_symbol as cs

    sym, _ = cs(proto)
    args, _, _ = sym.infer_shape(data=(1, 3, 9, 9))
    shapes = dict(zip(sym.list_arguments(), args))
    assert shapes["c_weight"] == (4, 3, 3, 5)

    # missing num_output raises a descriptive error naming the layer
    with pytest.raises(ValueError, match="conv_noout.*num_output"):
        cs("""
        input: "data"
        layer { name: "conv_noout" type: "Convolution" bottom: "data"
                top: "c" convolution_param { kernel_size: 3 } }
        """)


def test_caffemodel_legacy_4d_fc_blob_and_truncation():
    from caffe_converter import convert_model, read_caffemodel

    rs = np.random.RandomState(3)
    layers, parts = _weights_fixture(rs)
    # re-encode the FC weight with legacy 4-d (1,1,N,D) dims
    fc_w = parts[6]
    layers = [(n, bl) if n != "fc"
              else (n, [fc_w.reshape(1, 1, *fc_w.shape), bl[1]])
              for n, bl in layers]
    model = _caffemodel_bytes(layers, legacy=True)
    sym, arg_params, _, _ = convert_model(_WEIGHTS_PROTOTXT, model)
    assert arg_params["fc_weight"].shape == fc_w.shape
    np.testing.assert_allclose(arg_params["fc_weight"].asnumpy(), fc_w)

    # a truncated file must fail loudly, not produce a corrupt checkpoint
    with pytest.raises(ValueError, match="truncated"):
        read_caffemodel(model[:len(model) - 7])
