"""Caffe prototxt -> Symbol converter (tools/caffe_converter.py; the
reference tools/caffe_converter/convert_symbol.py analogue)."""

import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

from caffe_converter import convert_symbol, parse_prototxt  # noqa: E402

_LENET_PROTOTXT = """
name: "LeNet"
input: "data"
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 20 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "conv2"
  type: "Convolution"
  bottom: "pool1"
  top: "conv2"
  convolution_param { num_output: 50 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool2"
  type: "Pooling"
  bottom: "conv2"
  top: "pool2"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool2"
  top: "ip1"
  inner_product_param { num_output: 500 }
}
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer {
  name: "ip2"
  type: "InnerProduct"
  bottom: "ip1"
  top: "ip2"
  inner_product_param { num_output: 10 }
}
layer {
  name: "loss"
  type: "SoftmaxWithLoss"
  bottom: "ip2"
  bottom: "label"
  top: "loss"
}
"""


def test_parse_prototxt_structure():
    net = parse_prototxt(_LENET_PROTOTXT)
    assert net["name"] == "LeNet"
    layers = net["layer"]
    assert len(layers) == 8
    assert layers[0]["convolution_param"]["num_output"] == 20
    assert layers[1]["pooling_param"]["pool"] == "MAX"
    assert layers[-1]["bottom"] == ["ip2", "label"]


def test_convert_lenet_trains():
    sym, input_name = convert_symbol(_LENET_PROTOTXT)
    assert input_name == "data"
    args = sym.list_arguments()
    assert "conv1_weight" in args and "ip2_bias" in args

    # converted LeNet must train end to end on synthetic digits
    rng = np.random.RandomState(0)
    y = rng.randint(0, 10, 128).astype(np.float32)
    # separable by mean brightness: class c images sit at intensity c/10
    x = (rng.rand(128, 1, 28, 28) * 0.1
         + y[:, None, None, None] / 10.0).astype(np.float32)
    it = mx.io.NDArrayIter(x, {"label": y}, batch_size=32, shuffle=True)
    mod = mx.mod.Module(sym, context=mx.cpu(), data_names=("data",),
                        label_names=("label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.002})
    metric = mx.metric.Accuracy()
    for epoch in range(25):
        it.reset()
        metric.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
            mod.update_metric(metric, b.label)
    assert metric.get()[1] > 0.8, metric.get()


def test_convert_vgg_style_blocks_and_eltwise():
    proto = """
    input: "data"
    layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1 } }
    layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
    layer { name: "c2" type: "Convolution" bottom: "c1" top: "c2"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1 } }
    layer { name: "sum" type: "Eltwise" bottom: "c1" bottom: "c2" top: "sum" }
    layer { name: "gp" type: "Pooling" bottom: "sum" top: "gp"
            pooling_param { pool: AVE global_pooling: true } }
    layer { name: "fc" type: "InnerProduct" bottom: "gp" top: "fc"
            inner_product_param { num_output: 4 } }
    layer { name: "prob" type: "Softmax" bottom: "fc" top: "prob" }
    """
    sym, _ = convert_symbol(proto)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(2, 3, 16, 16))
    rng = np.random.RandomState(1)
    for n, a in exe.arg_dict.items():
        if n != "data":
            a[:] = rng.uniform(-0.1, 0.1, a.shape).astype(np.float32)
    exe.arg_dict["data"][:] = rng.rand(2, 3, 16, 16).astype(np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 4)
    assert np.allclose(out.sum(1), 1.0, atol=1e-4)


def test_convert_training_prototxt_with_data_layer_and_bn():
    """Real-world shapes: a Data layer with data AND label tops, lowercase
    boolean tokens, BatchNorm+Scale pairs, and Eltwise coeffs."""
    proto = """
    layer { name: "mnist" type: "Data" top: "data" top: "label" }
    layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1
                                bias_term: false } }
    layer { name: "bn1" type: "BatchNorm" bottom: "c1" top: "c1" }
    layer { name: "sc1" type: "Scale" bottom: "c1" top: "c1" }
    layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
    layer { name: "c2" type: "Convolution" bottom: "c1" top: "c2"
            convolution_param { num_output: 8 kernel_size: 3 pad: 1 } }
    layer { name: "diff" type: "Eltwise" bottom: "c1" bottom: "c2" top: "diff"
            eltwise_param { operation: SUM coeff: 1 coeff: -1 } }
    layer { name: "gp" type: "Pooling" bottom: "diff" top: "gp"
            pooling_param { pool: AVE global_pooling: true } }
    layer { name: "fc" type: "InnerProduct" bottom: "gp" top: "fc"
            inner_product_param { num_output: 3 } }
    layer { name: "loss" type: "SoftmaxWithLoss" bottom: "fc"
            bottom: "label" top: "loss" }
    """
    sym, input_name = convert_symbol(proto)
    assert input_name == "data"
    args = sym.list_arguments()
    assert "label" in args          # the Data layer's second top
    assert "c1_weight" in args and "c1_bias" not in args  # bias_term false
    assert "bn1_gamma" in args      # learnable (Scale folded, fix_gamma off)
    exe = sym.simple_bind(mx.cpu(), data=(2, 3, 8, 8), label=(2,))
    rng = np.random.RandomState(0)
    for n, a in exe.arg_dict.items():
        if n not in ("data", "label"):
            a[:] = rng.uniform(-0.2, 0.2, a.shape).astype(np.float32)
    exe.arg_dict["data"][:] = rng.rand(2, 3, 8, 8).astype(np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 3) and np.allclose(out.sum(1), 1, atol=1e-4)

    # standalone Scale refuses loudly
    with pytest.raises(ValueError):
        convert_symbol("""
        input: "data"
        layer { name: "s" type: "Scale" bottom: "data" top: "s" }
        """)
