"""Model-zoo symbols build, infer shapes, and run a forward pass.

Covers the reference's symbol library (example/image-classification/
symbols): alexnet, googlenet, inception-bn, inception-v3, resnet,
resnext, vgg, mlp, lenet — each must bind and produce (batch,
num_classes) probabilities.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models

_CASES = [
    ("mlp", lambda: models.mlp(), (2, 784)),
    ("lenet", lambda: models.lenet(), (2, 1, 28, 28)),
    ("alexnet", lambda: models.alexnet(num_classes=10), (2, 3, 224, 224)),
    ("googlenet", lambda: models.googlenet(num_classes=10), (2, 3, 224, 224)),
    ("inception-bn", lambda: models.inception_bn(num_classes=10),
     (2, 3, 224, 224)),
    ("inception-v3", lambda: models.inception_v3(num_classes=10),
     (2, 3, 299, 299)),
    ("resnet-18", lambda: models.resnet(num_classes=10, num_layers=18),
     (2, 3, 224, 224)),
    ("resnext-50", lambda: models.resnext(num_classes=10, num_layers=50),
     (2, 3, 224, 224)),
    ("vgg-16", lambda: models.vgg(num_classes=10), (2, 3, 224, 224)),
    ("inception-resnet-v2",
     lambda: models.inception_resnet_v2(num_classes=10), (2, 3, 299, 299)),
]


@pytest.mark.parametrize("name,factory,dshape", _CASES,
                         ids=[c[0] for c in _CASES])
def test_model_builds_and_forwards(name, factory, dshape):
    net = factory()
    exe = net.simple_bind(mx.cpu(), data=dshape,
                          softmax_label=(dshape[0],))
    rng = np.random.RandomState(0)
    for n, arr in exe.arg_dict.items():
        if n not in ("data", "softmax_label"):
            arr[:] = mx.nd.array(
                rng.uniform(-0.05, 0.05, arr.shape).astype(np.float32)
            )
    exe.arg_dict["data"][:] = mx.nd.array(
        rng.rand(*dshape).astype(np.float32)
    )
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (dshape[0], 10)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-3), "not a softmax"
    assert np.isfinite(out).all()


def test_inception_resnet_v2_reference_channel_plan():
    """The stage widths must match the reference file's exact plan
    (including its 129-channel block17 tower): mixed_5b=320,
    reduction_a=1088, reduction_b=2080, head=1536."""
    net = models.inception_resnet_v2(num_classes=10)
    internals = net.get_internals()
    shapes = {}
    for name in ("mixed_5b", "reduction_a", "reduction_b"):
        s_out = internals[name + "_output"]
        _, out, _ = s_out.infer_shape(data=(1, 3, 299, 299))
        shapes[name] = out[0]
    assert shapes["mixed_5b"][1] == 320
    assert shapes["reduction_a"][1] == 1088
    assert shapes["reduction_b"][1] == 2080


@pytest.mark.parametrize("factory,dshape", [
    (lambda dt: models.resnet(num_classes=10, num_layers=18,
                              image_shape="3,64,64", dtype=dt),
     (2, 3, 64, 64)),
    (lambda dt: models.alexnet(num_classes=10, dtype=dt), (2, 3, 224, 224)),
], ids=["resnet18-bf16", "alexnet-bf16"])
def test_bf16_recipe_eval_numerics(factory, dshape):
    """The bfloat16 recipe (reference resnet_fp16/alexnet_fp16 analogue):
    same params, trunk cast to bf16, classifier in f32 — eval outputs must
    track the f32 symbol within bf16 tolerance and still be a softmax."""
    rng = np.random.RandomState(0)
    x = rng.rand(*dshape).astype(np.float32)
    outs = {}
    for dt in ("float32", "bfloat16"):
        net = factory(dt)
        exe = net.simple_bind(mx.cpu(), grad_req="null", data=dshape,
                              softmax_label=(dshape[0],))
        r = np.random.RandomState(1)
        for n, arr in exe.arg_dict.items():
            if n not in ("data", "softmax_label"):
                arr[:] = mx.nd.array(
                    r.uniform(-0.05, 0.05, arr.shape).astype(np.float32))
        exe.arg_dict["data"][:] = mx.nd.array(x)
        outs[dt] = exe.forward(is_train=False)[0].asnumpy()
    assert np.allclose(outs["bfloat16"].sum(axis=1), 1.0, atol=1e-2)
    # bf16 trunk: ~3 decimal digits; logits differences are modest
    assert np.abs(outs["bfloat16"] - outs["float32"]).max() < 0.1
    assert np.abs(outs["bfloat16"] - outs["float32"]).mean() < 0.02
