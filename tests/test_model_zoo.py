"""Model-zoo symbols build, infer shapes, and run a forward pass.

Covers the reference's symbol library (example/image-classification/
symbols): alexnet, googlenet, inception-bn, inception-v3, resnet,
resnext, vgg, mlp, lenet — each must bind and produce (batch,
num_classes) probabilities.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models

_CASES = [
    ("mlp", lambda: models.mlp(), (2, 784)),
    ("lenet", lambda: models.lenet(), (2, 1, 28, 28)),
    ("alexnet", lambda: models.alexnet(num_classes=10), (2, 3, 224, 224)),
    ("googlenet", lambda: models.googlenet(num_classes=10), (2, 3, 224, 224)),
    ("inception-bn", lambda: models.inception_bn(num_classes=10),
     (2, 3, 224, 224)),
    ("inception-v3", lambda: models.inception_v3(num_classes=10),
     (2, 3, 299, 299)),
    ("resnet-18", lambda: models.resnet(num_classes=10, num_layers=18),
     (2, 3, 224, 224)),
    ("resnext-50", lambda: models.resnext(num_classes=10, num_layers=50),
     (2, 3, 224, 224)),
    ("vgg-16", lambda: models.vgg(num_classes=10), (2, 3, 224, 224)),
]


@pytest.mark.parametrize("name,factory,dshape", _CASES,
                         ids=[c[0] for c in _CASES])
def test_model_builds_and_forwards(name, factory, dshape):
    net = factory()
    exe = net.simple_bind(mx.cpu(), data=dshape,
                          softmax_label=(dshape[0],))
    rng = np.random.RandomState(0)
    for n, arr in exe.arg_dict.items():
        if n not in ("data", "softmax_label"):
            arr[:] = mx.nd.array(
                rng.uniform(-0.05, 0.05, arr.shape).astype(np.float32)
            )
    exe.arg_dict["data"][:] = mx.nd.array(
        rng.rand(*dshape).astype(np.float32)
    )
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (dshape[0], 10)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-3), "not a softmax"
    assert np.isfinite(out).all()
