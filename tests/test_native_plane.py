"""Native C++ data plane + C predict ABI shim.

The data plane (``mxnet_tpu/native/io_plane.cpp``) replaces the python
decode/augment path with libjpeg + std::thread workers — the analogue of
the reference's ``iter_image_recordio_2.cc`` OpenMP pipeline. The predict
shim (``c_predict_api.cpp``) exposes the reference's MXPred* C ABI; the
test compiles and runs a real C client against it.
"""

import ctypes
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native
from mxnet_tpu.recordio import MXRecordIO, pack_img
from mxnet_tpu.test_utils import assert_almost_equal

cv2 = pytest.importorskip("cv2")

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _make_rec(path, n=6, size=48, quality=98):
    rng = np.random.RandomState(0)
    rec = MXRecordIO(path, "w")
    imgs = []
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        imgs.append(img)
        rec.write(pack_img((0, float(i), i, 0), img, quality=quality))
    rec.close()
    return imgs


def test_native_scan_matches_python(tmp_path):
    path = str(tmp_path / "scan.rec")
    _make_rec(path)
    offs = native.scan(path)
    # python-side offsets must agree
    rec = MXRecordIO(path, "r")
    py_offs = []
    while True:
        pos = rec.tell()
        if rec.read() is None:
            break
        py_offs.append(pos)
    rec.close()
    assert offs.tolist() == py_offs


def test_native_decode_matches_cv2(tmp_path):
    path = str(tmp_path / "dec.rec")
    imgs = _make_rec(path)
    offs = native.scan(path)
    data, labels, ok = native.load_batch(path, offs, (3, 48, 48))
    assert ok == len(imgs)
    assert labels[:, 0].tolist() == list(range(len(imgs)))
    for i, img in enumerate(imgs):
        ref = cv2.cvtColor(
            cv2.imdecode(
                cv2.imencode(".jpg", img, [cv2.IMWRITE_JPEG_QUALITY, 98])[1],
                cv2.IMREAD_COLOR,
            ),
            cv2.COLOR_BGR2RGB,
        ).astype(np.float32)
        got = data[i].transpose(1, 2, 0)
        assert np.abs(got - ref).mean() < 1.0  # idct implementations differ


def test_native_normalisation_and_mirror(tmp_path):
    path = str(tmp_path / "norm.rec")
    imgs = _make_rec(path, n=2)
    offs = native.scan(path)
    data, _, _ = native.load_batch(
        path, offs, (3, 48, 48), mean=(10, 20, 30), std=(2, 2, 2), scale=0.5
    )
    plain, _, _ = native.load_batch(path, offs, (3, 48, 48))
    expect = (plain[0] - np.array([10, 20, 30], np.float32)[:, None, None]) / 2 * 0.5
    assert_almost_equal(data[0], expect, rtol=1e-5, atol=1e-4)


def test_image_record_iter_uses_native(tmp_path):
    path = str(tmp_path / "iter.rec")
    _make_rec(path, n=8)
    it = mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 48, 48), batch_size=4,
    )
    assert getattr(it, "_native", False), "native plane not selected"
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 48, 48)
    # native and python planes agree on un-augmented batches
    it_py = mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 48, 48), batch_size=4,
        use_native=False,
    )
    py_b = next(it_py)
    assert np.abs(
        batches[0].data[0].asnumpy() - py_b.data[0].asnumpy()
    ).mean() < 1.0
    assert_almost_equal(batches[0].label[0].asnumpy(),
                        py_b.label[0].asnumpy())


_C_CLIENT = r"""
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>

typedef void* PredictorHandle;
extern int MXPredCreate(const char*, const void*, int, int, int, uint32_t,
                        const char**, const uint32_t*, const uint32_t*,
                        PredictorHandle*);
extern int MXPredSetInput(PredictorHandle, const char*, const float*, uint32_t);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutputShape(PredictorHandle, uint32_t, uint32_t**, uint32_t*);
extern int MXPredGetOutput(PredictorHandle, uint32_t, float*, uint32_t);
extern int MXPredFree(PredictorHandle);
extern const char* MXGetLastError();

int main(int argc, char** argv) {
  FILE* fs = fopen(argv[1], "rb");
  fseek(fs, 0, SEEK_END); long slen = ftell(fs); fseek(fs, 0, SEEK_SET);
  char* json = malloc(slen + 1);
  if (fread(json, 1, slen, fs) != (size_t)slen) return 2;
  json[slen] = 0; fclose(fs);
  FILE* fp = fopen(argv[2], "rb");
  fseek(fp, 0, SEEK_END); long plen = ftell(fp); fseek(fp, 0, SEEK_SET);
  char* params = malloc(plen);
  if (fread(params, 1, plen, fp) != (size_t)plen) return 2;
  fclose(fp);

  const char* keys[] = {"data"};
  uint32_t indptr[] = {0, 2};
  uint32_t dims[] = {2, 6};
  PredictorHandle h;
  if (MXPredCreate(json, params, (int)plen, 1, 0, 1, keys, indptr, dims, &h)) {
    fprintf(stderr, "create: %s\n", MXGetLastError()); return 1;
  }
  float input[12];
  for (int i = 0; i < 12; ++i) input[i] = 0.1f * i;
  if (MXPredSetInput(h, "data", input, 12)) return 1;
  if (MXPredForward(h)) { fprintf(stderr, "fwd: %s\n", MXGetLastError()); return 1; }
  uint32_t* shp; uint32_t ndim;
  if (MXPredGetOutputShape(h, 0, &shp, &ndim)) return 1;
  uint32_t total = 1;
  for (uint32_t i = 0; i < ndim; ++i) total *= shp[i];
  float* out = malloc(total * sizeof(float));
  if (MXPredGetOutput(h, 0, out, total)) return 1;
  for (uint32_t i = 0; i < total; ++i) printf("%.6f\n", out[i]);
  MXPredFree(h);
  return 0;
}
"""


def test_c_predict_abi_end_to_end(tmp_path):
    """Compile a C client against the shim; outputs must match Python."""
    # model + checkpoint
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc"), name="softmax"
    )
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mx.random.seed(0)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    # build the shim + client
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    shim = str(tmp_path / "libmxtpu_predict.so")
    r = subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC",
         os.path.join(_ROOT, "mxnet_tpu", "native", "c_predict_api.cpp"),
         "-o", shim, f"-I{inc}", f"-L{libdir}",
         f"-lpython{sysconfig.get_python_version()}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    csrc = str(tmp_path / "client.c")
    with open(csrc, "w") as f:
        f.write(_C_CLIENT)
    client = str(tmp_path / "client")
    r = subprocess.run(
        ["gcc", "-O2", csrc, "-o", client, shim, f"-Wl,-rpath,{tmp_path}",
         f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [client, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert r.returncode == 0, r.stderr + r.stdout
    got = np.array([float(x) for x in r.stdout.split()], np.float32)

    # python-side oracle
    x = (0.1 * np.arange(12, dtype=np.float32)).reshape(2, 6)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], []), is_train=False)
    expect = mod.get_outputs()[0].asnumpy().ravel()
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)


def test_png_rec_falls_back_to_python_plane(tmp_path):
    """Auto-selection sniffs image magic: PNG payloads (which the native
    JPEG decoder can't handle) route to the cv2 path instead of erroring
    mid-epoch."""
    path = str(tmp_path / "png.rec")
    rng = np.random.RandomState(0)
    rec = MXRecordIO(path, "w")
    for i in range(4):
        img = rng.randint(0, 255, (32, 32, 3), np.uint8)
        rec.write(pack_img((0, float(i), i, 0), img, img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 32, 32), batch_size=2,
    )
    assert not it._native, "PNG rec must not select the native JPEG plane"
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (2, 3, 32, 32)


_C_PARTIAL_CLIENT = r"""
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef void* PredictorHandle;
typedef void* NDListHandle;
extern int MXPredCreatePartialOut(const char*, const void*, int, int, int,
                                  uint32_t, const char**, const uint32_t*,
                                  const uint32_t*, uint32_t, const char**,
                                  PredictorHandle*);
extern int MXPredSetInput(PredictorHandle, const char*, const float*, uint32_t);
extern int MXPredForward(PredictorHandle);
extern int MXPredPartialForward(PredictorHandle, int, int*);
extern int MXPredGetOutputShape(PredictorHandle, uint32_t, uint32_t**, uint32_t*);
extern int MXPredGetOutput(PredictorHandle, uint32_t, float*, uint32_t);
extern int MXPredFree(PredictorHandle);
extern int MXNDListCreate(const char*, int, NDListHandle*, uint32_t*);
extern int MXNDListGet(NDListHandle, uint32_t, const char**, const float**,
                       const uint32_t**, uint32_t*);
extern int MXNDListFree(NDListHandle);
extern const char* MXGetLastError();

int main(int argc, char** argv) {
  FILE* fs = fopen(argv[1], "rb");
  fseek(fs, 0, SEEK_END); long slen = ftell(fs); fseek(fs, 0, SEEK_SET);
  char* json = malloc(slen + 1);
  if (fread(json, 1, slen, fs) != (size_t)slen) return 2;
  json[slen] = 0; fclose(fs);
  FILE* fp = fopen(argv[2], "rb");
  fseek(fp, 0, SEEK_END); long plen = ftell(fp); fseek(fp, 0, SEEK_SET);
  char* params = malloc(plen);
  if (fread(params, 1, plen, fp) != (size_t)plen) return 2;
  fclose(fp);

  /* NDList: read the params blob itself as an ndarray list */
  NDListHandle nl; uint32_t nlen;
  if (MXNDListCreate(params, (int)plen, &nl, &nlen)) {
    fprintf(stderr, "ndlist: %s\n", MXGetLastError()); return 1;
  }
  const char* k0; const float* d0; const uint32_t* s0; uint32_t nd0;
  if (MXNDListGet(nl, 0, &k0, &d0, &s0, &nd0)) return 1;
  printf("NDLIST %u %s %u\n", nlen, k0, nd0);
  MXNDListFree(nl);

  /* partial-out predictor on the fc layer (pre-softmax features) */
  const char* keys[] = {"data"};
  uint32_t indptr[] = {0, 2};
  uint32_t dims[] = {2, 6};
  const char* outs[] = {"fc"};
  PredictorHandle h;
  if (MXPredCreatePartialOut(json, params, (int)plen, 1, 0, 1, keys, indptr,
                             dims, 1, outs, &h)) {
    fprintf(stderr, "create: %s\n", MXGetLastError()); return 1;
  }
  float input[12];
  for (int i = 0; i < 12; ++i) input[i] = 0.1f * i;
  if (MXPredSetInput(h, "data", input, 12)) return 1;
  if (MXPredForward(h)) { fprintf(stderr, "fwd: %s\n", MXGetLastError()); return 1; }
  uint32_t* shp; uint32_t ndim;
  if (MXPredGetOutputShape(h, 0, &shp, &ndim)) return 1;
  uint32_t total = 1;
  for (uint32_t i = 0; i < ndim; ++i) total *= shp[i];
  float* out = malloc(total * sizeof(float));
  if (MXPredGetOutput(h, 0, out, total)) return 1;
  printf("FEAT");
  for (uint32_t i = 0; i < total; ++i) printf(" %.6f", out[i]);
  printf("\n");

  /* step-wise execution to completion */
  int left = 1;
  int step = 0;
  while (left > 0) {
    if (MXPredPartialForward(h, step, &left)) {
      fprintf(stderr, "partial: %s\n", MXGetLastError()); return 1;
    }
    step++;
  }
  if (MXPredGetOutputShape(h, 0, &shp, &ndim)) return 1;
  total = 1;
  for (uint32_t i = 0; i < ndim; ++i) total *= shp[i];
  out = realloc(out, total * sizeof(float));
  if (MXPredGetOutput(h, 0, out, total)) return 1;
  printf("STEPPED %d", step);
  for (uint32_t i = 0; i < total && i < 4; ++i) printf(" %.6f", out[i]);
  printf("\n");
  MXPredFree(h);
  return 0;
}
"""


def test_c_predict_partial_out_and_ndlist(tmp_path):
    """MXPredCreatePartialOut + MXPredPartialForward + MXNDList*: feature
    extraction and step-wise execution through the pure-C ABI, against
    Python oracles."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))],
             label_shapes=[("softmax_label", (2,))])
    mx.random.seed(4)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    shim = str(tmp_path / "libmxtpu_predict.so")
    r = subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC",
         os.path.join(_ROOT, "mxnet_tpu", "native", "c_predict_api.cpp"),
         "-o", shim, f"-I{inc}", f"-L{libdir}",
         f"-lpython{sysconfig.get_python_version()}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr
    src = str(tmp_path / "partial_client.c")
    with open(src, "w") as f:
        f.write(_C_PARTIAL_CLIENT)
    exe = str(tmp_path / "partial_client")
    r = subprocess.run(
        ["gcc", "-O2", src, "-o", exe, shim, f"-Wl,-rpath,{tmp_path}",
         f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [exe, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr + r.stdout
    lines = r.stdout.strip().splitlines()
    ndl = [l for l in lines if l.startswith("NDLIST")][0].split()
    assert int(ndl[1]) == 2  # fc weight + bias entries
    feat = [l for l in lines if l.startswith("FEAT")][0].split()[1:]
    got = np.array([float(x) for x in feat], np.float32).reshape(2, 4)

    # python oracle: the fc features (pre-softmax)
    x = (0.1 * np.arange(12, dtype=np.float32)).reshape(2, 6)
    feats = fc
    fexe = feats.simple_bind(mx.cpu(), grad_req="null", data=(2, 6))
    args, auxs = mod.get_params()
    fexe.copy_params_from(args, auxs)
    fexe.arg_dict["data"][:] = x
    expect = fexe.forward(is_train=False)[0].asnumpy()
    assert_almost_equal(got, expect, rtol=1e-4, atol=1e-5)

    stepped = [l for l in lines if l.startswith("STEPPED")][0].split()
    got_step = np.array([float(v) for v in stepped[2:]], np.float32)
    assert_almost_equal(got_step, expect.ravel()[:4], rtol=1e-4, atol=1e-5)
