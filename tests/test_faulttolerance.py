"""Non-finite-gradient guard and transient-IO retry.

The guard folds an all-finite reduction into the fused train step
(MXNET_NONFINITE_GUARD): a NaN/Inf gradient batch must leave params
bit-identical, increment fit.nonfinite_skip, and add ZERO host-blocking
syncs (asserted on the framework's own telemetry counters, like
tests/test_async_pipeline.py). Escalation: rollback restores the last
checkpoint after K consecutive skips, then raises; raise fails fast.
RetryingIter turns transient data-source failures into backoff+retry.
"""

import os

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import faultinject as fi
from mxnet_tpu import telemetry as tm
from mxnet_tpu.base import MXNetError


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=8, name="fc1"),
        act_type="relu")
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")


def _iter(n=32, batch=8):
    rng = np.random.RandomState(0)
    return mx.io.NDArrayIter(
        rng.randn(n, 10).astype(np.float32),
        rng.randint(0, 4, (n,)).astype(np.float32), batch_size=batch)


def _module(it):
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    return mod


_SYNC = ("ndarray.asnumpy", "ndarray.wait_to_read", "metric.numpy_fallback")


def test_guard_skip_leaves_params_bit_identical(monkeypatch):
    """A NaN-gradient step under guard=skip is a no-op for params,
    optimizer state AND BN-style aux, and counts [total, consecutive]."""
    monkeypatch.setenv("MXNET_NONFINITE_GUARD", "skip")
    it = _iter()
    mod = _module(it)
    clean = next(iter(it))
    mod.forward_backward(clean)
    mod.update()
    w0 = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy().copy()
    mom0 = {k: v for k, v in
            (mod._updater.states.items() if mod._updater else [])}

    bad = mx.io.DataBatch(
        data=[mx.nd.array(np.full((8, 10), np.nan, np.float32))],
        label=clean.label)
    mod.forward_backward(bad)
    mod.update()
    assert mod.nonfinite_stats() == (1, 1)
    w1 = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy()
    np.testing.assert_array_equal(w0, w1)
    # momentum state also untouched (same handles, same values)
    for k, s in mom0.items():
        np.testing.assert_array_equal(
            np.asarray(s._data if hasattr(s, "_data") else s),
            np.asarray(mod._updater.states[k]._data
                       if hasattr(mod._updater.states[k], "_data")
                       else mod._updater.states[k]))

    # a clean step resets the consecutive counter and trains again
    mod.forward_backward(clean)
    mod.update()
    assert mod.nonfinite_stats() == (1, 0)
    w2 = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy()
    assert not np.array_equal(w1, w2)


def test_guard_skip_in_fit_no_extra_syncs(monkeypatch):
    """fit + injected NaN batch: fit.nonfinite_skip increments, the run
    completes, and the guard adds zero per-batch asnumpy /
    wait_to_read / numpy-fallback syncs (telemetry-counter-verified)."""
    monkeypatch.setenv("MXNET_NONFINITE_GUARD", "skip")
    monkeypatch.setenv("MXNET_FI_NAN_BATCHES", "2")
    fi.reset()
    tm.reset()
    it = _iter()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=2,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    assert tm.counter("fit.nonfinite_skip").value == 1
    for name in _SYNC:
        assert tm.counter(name).value == 0, name
    assert tm.counter("metric.drain_sync").value == 2  # one per epoch
    assert tm.counter("fit.batches").value == 8
    assert mod.nonfinite_stats()[0] == 1


def test_guard_off_by_default():
    it = _iter()
    mod = _module(it)
    clean = next(iter(it))
    mod.forward_backward(clean)
    mod.update()
    w0 = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy().copy()
    bad = mx.io.DataBatch(
        data=[mx.nd.array(np.full((8, 10), np.nan, np.float32))],
        label=clean.label)
    mod.forward_backward(bad)
    mod.update()
    # ungated: NaN propagates into the weights (the historical behavior)
    w1 = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy()
    assert np.isnan(w1).any() and not np.array_equal(w0, w1)
    assert mod.nonfinite_stats() == (0, 0)


def test_guard_imperative_path(monkeypatch):
    """The guard also covers the un-fused per-param update path (NaiveEngine
    / bulk-exec off), via a host-side check."""
    monkeypatch.setenv("MXNET_NONFINITE_GUARD", "skip")
    monkeypatch.setenv("MXNET_EXEC_BULK_EXEC_TRAIN", "0")
    it = _iter()
    mod = _module(it)
    clean = next(iter(it))
    mod.forward_backward(clean)
    mod.update()
    w0 = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy().copy()
    bad = mx.io.DataBatch(
        data=[mx.nd.array(np.full((8, 10), np.nan, np.float32))],
        label=clean.label)
    mod.forward_backward(bad)
    mod.update()
    np.testing.assert_array_equal(
        w0, mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy())
    assert mod.nonfinite_stats() == (1, 1)


def test_guard_raise_fails_fast(monkeypatch):
    monkeypatch.setenv("MXNET_NONFINITE_GUARD", "raise")
    monkeypatch.setenv("MXNET_FI_NAN_BATCHES", "1")
    fi.reset()
    it = _iter()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with pytest.raises(MXNetError, match="non-finite gradients"):
        mod.fit(it, num_epoch=1,
                optimizer_params={"learning_rate": 0.1})


def test_guard_rollback_then_raise(monkeypatch, tmp_path):
    """rollback escalation: after K consecutive skips the last checkpoint
    is restored (fit.nonfinite_rollback); a blowup persisting past the
    rollback raises instead of spinning forever."""
    monkeypatch.setenv("MXNET_NONFINITE_GUARD", "rollback")
    monkeypatch.setenv("MXNET_NONFINITE_TOLERANCE", "2")
    # every batch from epoch 1 on is NaN (4 batches/epoch)
    monkeypatch.setenv("MXNET_FI_NAN_BATCHES",
                       ",".join(str(i) for i in range(4, 12)))
    fi.reset()
    d = str(tmp_path / "ckpts")
    r0 = tm.counter("fit.nonfinite_rollback").value
    it = _iter()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with pytest.raises(MXNetError, match="persisted after a checkpoint"):
        mod.fit(it, num_epoch=3,
                optimizer_params={"learning_rate": 0.1},
                checkpoint=mx.CheckpointConfig(d, period=1))
    assert tm.counter("fit.nonfinite_rollback").value == r0 + 1


def test_guard_rollback_without_checkpoint_raises(monkeypatch):
    monkeypatch.setenv("MXNET_NONFINITE_GUARD", "rollback")
    monkeypatch.setenv("MXNET_NONFINITE_TOLERANCE", "1")
    monkeypatch.setenv("MXNET_FI_NAN_BATCHES", "1,2,3")
    fi.reset()
    it = _iter()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with pytest.raises(MXNetError, match="no checkpoint"):
        mod.fit(it, num_epoch=1,
                optimizer_params={"learning_rate": 0.1})


# --- RetryingIter -----------------------------------------------------------

def test_retrying_iter_recovers_transient_failures():
    base = _iter()
    ref = [b.data[0].asnumpy() for b in base]
    base.reset()
    flaky = fi.FlakyIter(base, raise_at={0, 2})
    a0 = tm.counter("io.retry.attempts").value
    it = mx.io.RetryingIter(flaky, max_retries=2, backoff=0.001)
    got = [b.data[0].asnumpy() for b in it]
    assert len(got) == len(ref)
    for a, b in zip(ref, got):
        np.testing.assert_array_equal(a, b)
    assert tm.counter("io.retry.attempts").value == a0 + 2
    # reset rearms the fault; retry absorbs it again
    it.reset()
    assert len([b for b in it]) == len(ref)


def test_retrying_iter_gives_up():
    class AlwaysDown(mx.io.DataIter):
        def next(self):
            raise ConnectionError("data service unreachable")

        def reset(self):
            pass

    g0 = tm.counter("io.retry.giveup").value
    it = mx.io.RetryingIter(AlwaysDown(), max_retries=2, backoff=0.001)
    with pytest.raises(ConnectionError):
        it.next()
    assert tm.counter("io.retry.giveup").value == g0 + 1


def test_fit_retries_flaky_source(monkeypatch):
    """MXNET_IO_RETRY wraps the training iterator: a source raising a
    transient IOError once per epoch still completes the fit."""
    monkeypatch.setenv("MXNET_IO_RETRY", "2")
    monkeypatch.setenv("MXNET_IO_RETRY_BACKOFF", "0.001")
    monkeypatch.setenv("MXNET_FI_ITER_RAISE_BATCHES", "1")
    fi.reset()
    it = fi.FlakyIter(_iter())
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    a0 = tm.counter("io.retry.attempts").value
    mod.fit(it, num_epoch=2,
            optimizer_params={"learning_rate": 0.1})
    assert tm.counter("io.retry.attempts").value == a0 + 2  # once per epoch
