"""Predictor coverage (reference c_predict_api semantics): creation from
JSON/file/blob, partial-output predictors, partial_forward, reshape
validation, and the input-dtype contract (integer inputs bind and load as
integers — no silent float32 round-trip)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.base import MXNetError
from mxnet_tpu.predictor import (Predictor, create_predictor,
                                 create_predictor_partial, load_ndlist)


@pytest.fixture(scope="module")
def mlp_model(tmp_path_factory):
    """(symbol, params, json_str, symbol_file, params_file, blob_bytes)."""
    sym = models.mlp(num_classes=4)
    arg_shapes, _, _ = sym.infer_shape(data=(1, 6), softmax_label=(1,))
    rng = np.random.RandomState(0)
    params = {}
    for n, s in zip(sym.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        params[n] = mx.nd.array(rng.randn(*s).astype(np.float32))
    prefix = str(tmp_path_factory.mktemp("predictor") / "mlp")
    mx.model.save_checkpoint(prefix, 0, sym, params, {})
    sym_file = f"{prefix}-symbol.json"
    params_file = f"{prefix}-0000.params"
    with open(params_file, "rb") as f:
        blob = f.read()
    return sym, params, sym.tojson(), sym_file, params_file, blob


def _x(n=1, seed=3):
    return np.random.RandomState(seed).uniform(-1, 1, (n, 6)) \
        .astype(np.float32)


def test_create_from_json_file_and_blob(mlp_model):
    sym, params, json_str, sym_file, params_file, blob = mlp_model
    x = _x()
    outs = []
    for pred in (
        Predictor(json_str, params_file, {"data": (1, 6)}),
        Predictor(sym_file, params_file, {"data": (1, 6)}),
        Predictor(sym, {f"arg:{k}": v for k, v in params.items()},
                  {"data": (1, 6)}),
        create_predictor(json_str, blob, {"data": (1, 6)}),  # C-shim path
    ):
        pred.set_input("data", x)
        pred.forward()
        assert pred.num_outputs == 1
        assert pred.get_output_shape(0) == (1, 4)
        outs.append(pred.get_output(0))
    for o in outs[1:]:  # same weights through every load path → same bytes
        assert o.tobytes() == outs[0].tobytes()
    s = np.asarray(outs[0]).sum(axis=1)
    np.testing.assert_allclose(s, 1.0, rtol=1e-5)  # softmax rows


def test_forward_kwargs_and_bytes_roundtrip(mlp_model):
    _, _, json_str, _, params_file, _ = mlp_model
    pred = Predictor(json_str, params_file, {"data": (2, 6)})
    x = _x(2)
    pred.forward(data=x)
    a = pred.get_output(0)
    pred.set_input_bytes("data", x.tobytes())
    pred.forward()
    assert pred.get_output(0).tobytes() == a.tobytes()
    assert len(pred.get_output_bytes(0)) == 2 * 4 * 4  # f32 (2,4)


def test_create_predictor_partial(mlp_model):
    _, _, json_str, _, params_file, blob = mlp_model
    # both the node name and the _output convention resolve
    for key in ("fc1", "fc1_output"):
        pred = create_predictor_partial(
            json_str, blob, {"data": (1, 6)}, [key])
        pred.forward(data=_x())
        assert pred.get_output_shape(0) == (1, 128)
    with pytest.raises(MXNetError):
        create_predictor_partial(
            json_str, blob, {"data": (1, 6)}, ["nonexistent_layer"])


def test_partial_forward(mlp_model):
    _, _, json_str, _, params_file, _ = mlp_model
    pred = Predictor(json_str, params_file, {"data": (1, 6)})
    x = _x()
    pred.forward(data=x)
    full = pred.get_output(0)
    total = sum(1 for nd in pred._exec.graph.topo if not nd.is_variable)
    remaining = pred.partial_forward(0)  # just the first op node
    assert remaining == total - 1
    remaining = pred.partial_forward(total - 1)  # the whole graph
    assert remaining == 0
    assert pred.get_output(0).tobytes() == full.tobytes()
    # next full forward clears the partial view
    pred.forward(data=x)
    assert pred.get_output(0).tobytes() == full.tobytes()


def test_reshape_rebinds_and_validates(mlp_model):
    _, _, json_str, _, params_file, _ = mlp_model
    pred = Predictor(json_str, params_file, {"data": (1, 6)})
    x1 = _x()
    pred.forward(data=x1)
    ref = pred.get_output(0)
    pred.reshape({"data": (3, 6)})
    x3 = np.concatenate([x1, _x(2, seed=5)])
    pred.forward(data=x3)
    assert pred.get_output_shape(0) == (3, 4)
    np.testing.assert_allclose(pred.get_output(0)[0], ref[0], rtol=1e-5,
                               atol=1e-12)

    # unknown input name: a clear error, not a silently stale binding
    with pytest.raises(MXNetError, match="not_an_input"):
        pred.reshape({"not_an_input": (1, 6)})
    # the failed reshape left the predictor usable at its old shape
    pred.forward(data=x3)
    assert pred.get_output_shape(0) == (3, 4)


def test_unknown_input_rejected_at_create(mlp_model):
    _, _, json_str, _, params_file, _ = mlp_model
    with pytest.raises(MXNetError, match="bogus"):
        Predictor(json_str, params_file, {"bogus": (1, 6)})


def test_int_inputs_preserved_exactly():
    """Integer inputs bound as integers survive set_input/set_input_bytes
    exactly. 2**24 + 1 is unrepresentable in float32 — the old forced
    np.float32 coercion rounded it to 2**24."""
    data = mx.sym.Variable("data")
    out = mx.sym.Flatten(data, name="flat")  # dtype-preserving graph
    pred = Predictor(out, {}, {"data": (1, 3)},
                     input_types={"data": "int32"})
    big = np.array([[2**24 + 1, 1, -7]], dtype=np.int64)
    pred.set_input("data", big)
    pred.forward()
    got = pred.get_output(0)
    assert got.dtype == np.int32
    assert got.tolist() == [[2**24 + 1, 1, -7]]

    # raw-byte ABI path reads the BOUND dtype, not forced float32
    pred.set_input_bytes(
        "data", np.array([[2**24 + 3, 0, 5]], np.int32).tobytes())
    pred.forward()
    assert pred.get_output(0).tolist() == [[2**24 + 3, 0, 5]]

    # unknown name fails with the framework error, not a bare KeyError
    with pytest.raises(MXNetError, match="not an input"):
        pred.set_input_bytes("bogus", b"\x00" * 12)


def test_float_inputs_still_coerce():
    """Float-bound inputs keep accepting python lists / int arrays
    (legacy behaviour: everything funnels to the bound float32)."""
    data = mx.sym.Variable("data")
    out = mx.sym.Flatten(data, name="flat")
    pred = Predictor(out, {}, {"data": (1, 2)})
    pred.set_input("data", [[1, 2]])
    pred.forward()
    got = pred.get_output(0)
    assert got.dtype == np.float32
    assert got.tolist() == [[1.0, 2.0]]


def test_input_types_validation():
    data = mx.sym.Variable("data")
    out = mx.sym.Flatten(data, name="flat")
    with pytest.raises(MXNetError, match="not inputs"):
        Predictor(out, {}, {"data": (1, 2)},
                  input_types={"wrong": "int32"})


def test_set_params_swaps_weights(mlp_model):
    sym, params, json_str, _, params_file, _ = mlp_model
    pred = Predictor(json_str, params_file, {"data": (1, 6)})
    x = _x()
    pred.forward(data=x)
    before = pred.get_output(0)
    scaled = {k: (v * 2.0) for k, v in params.items()}
    pred.set_params(scaled)
    pred.forward(data=x)
    after = pred.get_output(0)
    assert before.tobytes() != after.tobytes()
    # matches a predictor constructed with the new weights
    ref = Predictor(sym, {f"arg:{k}": v for k, v in scaled.items()},
                    {"data": (1, 6)})
    ref.forward(data=x)
    assert after.tobytes() == ref.get_output(0).tobytes()

    with pytest.raises(MXNetError, match="missing"):
        pred.set_params({"fc1_weight": scaled["fc1_weight"]})
    with pytest.raises(MXNetError, match="shape mismatch"):
        pred.set_params({k: mx.nd.zeros((1, 1)) for k in scaled})


def test_set_params_failure_is_atomic(mlp_model):
    """A set_params that fails partway (shape mismatch on a LATER key)
    must leave the bound net fully on the old weights — never a
    half-swapped mix of versions (the serving hot-reload contract)."""
    sym, params, json_str, _, params_file, _ = mlp_model
    pred = Predictor(json_str, params_file, {"data": (1, 6)})
    x = _x()
    pred.forward(data=x)
    before = pred.get_output(0)
    bad = {k: (v * 3.0) for k, v in params.items()}
    # corrupt the LAST key in iteration order so earlier entries would
    # already have been copied by a non-atomic swap
    last = list(bad)[-1]
    bad[last] = mx.nd.zeros((2, 2))
    with pytest.raises(MXNetError, match="shape mismatch"):
        pred.set_params(bad)
    pred.forward(data=x)
    assert pred.get_output(0).tobytes() == before.tobytes(), (
        "failed set_params left a half-swapped weight mix")
    # an unknown argument name fails the same way, weights untouched
    with pytest.raises(MXNetError, match="not a .*bound argument"):
        pred.set_params(dict({k: v * 3.0 for k, v in params.items()},
                             bogus_weight=mx.nd.zeros((1,))))
    pred.forward(data=x)
    assert pred.get_output(0).tobytes() == before.tobytes()


def test_load_ndlist(mlp_model):
    _, params, _, _, _, blob = mlp_model
    items = load_ndlist(blob)
    assert len(items) == len(params)
    assert all(k.startswith("arg:") for k, _ in items)
    assert all(v.dtype == np.float32 for _, v in items)
