"""NDArray tests (reference tests/python/unittest/test_ndarray.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, same


def test_ndarray_creation():
    a = mx.nd.array([1, 2, 3])
    assert a.shape == (3,)
    assert a.dtype == np.float32
    b = mx.nd.zeros((2, 3))
    assert same(b.asnumpy(), np.zeros((2, 3)))
    c = mx.nd.ones((2, 3), dtype="int32")
    assert c.dtype == np.int32
    d = mx.nd.full((2, 2), 7.5)
    assert same(d.asnumpy(), np.full((2, 2), 7.5, dtype=np.float32))
    e = mx.nd.arange(0, 10, 2)
    assert same(e.asnumpy(), np.arange(0, 10, 2, dtype=np.float32))


def test_ndarray_elementwise():
    rs = np.random.RandomState(0)
    x = rs.randn(3, 4).astype(np.float32)
    y = rs.randn(3, 4).astype(np.float32)
    a, b = mx.nd.array(x), mx.nd.array(y)
    assert_almost_equal((a + b).asnumpy(), x + y)
    assert_almost_equal((a - b).asnumpy(), x - y)
    assert_almost_equal((a * b).asnumpy(), x * y)
    assert_almost_equal((a / b).asnumpy(), x / y, rtol=1e-5, atol=1e-5)
    assert_almost_equal((a + 2).asnumpy(), x + 2)
    assert_almost_equal((2 - a).asnumpy(), 2 - x)
    assert_almost_equal((a ** 2).asnumpy(), x ** 2)
    assert_almost_equal((-a).asnumpy(), -x)
    assert_almost_equal(abs(a).asnumpy(), np.abs(x))


def test_ndarray_inplace():
    x = np.ones((2, 2), dtype=np.float32)
    a = mx.nd.array(x)
    a += 1
    assert same(a.asnumpy(), x + 1)
    a *= 3
    assert same(a.asnumpy(), (x + 1) * 3)
    a -= 2
    a /= 2
    assert_almost_equal(a.asnumpy(), ((x + 1) * 3 - 2) / 2)


def test_ndarray_setitem():
    a = mx.nd.zeros((3, 4))
    a[:] = 5
    assert same(a.asnumpy(), np.full((3, 4), 5, dtype=np.float32))
    a[1, 2] = 9
    expected = np.full((3, 4), 5, dtype=np.float32)
    expected[1, 2] = 9
    assert same(a.asnumpy(), expected)
    a[0] = np.arange(4)
    expected[0] = np.arange(4)
    assert same(a.asnumpy(), expected)


def test_ndarray_indexing():
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    a = mx.nd.array(x)
    assert same(a[1].asnumpy(), x[1])
    assert same(a[0, 1].asnumpy(), x[0, 1])
    assert same(a[:, 1:3].asnumpy(), x[:, 1:3])
    assert a[1, 2, 3].asscalar() == x[1, 2, 3]


def test_ndarray_reshape():
    a = mx.nd.arange(0, 24)
    b = a.reshape((2, 3, 4))
    assert b.shape == (2, 3, 4)
    c = b.reshape((-1, 4))
    assert c.shape == (6, 4)
    d = b.reshape((0, -1))  # mxnet special code 0 = copy dim
    assert d.shape == (2, 12)
    e = b.reshape((-3, 4))  # merge first two dims
    assert e.shape == (6, 4)


def test_ndarray_copy():
    a = mx.nd.array([[1, 2], [3, 4]])
    b = a.copy()
    b[0, 0] = 99
    assert a[0, 0].asscalar() == 1
    c = mx.nd.zeros((2, 2))
    a.copyto(c)
    assert same(c.asnumpy(), a.asnumpy())


def test_ndarray_dtype_cast():
    a = mx.nd.ones((2, 2))
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.astype("float16")
    assert c.dtype == np.float16


def test_ndarray_ops():
    rs = np.random.RandomState(3)
    x = rs.rand(4, 5).astype(np.float32) + 0.5
    a = mx.nd.array(x)
    assert_almost_equal(mx.nd.sqrt(a).asnumpy(), np.sqrt(x), rtol=1e-5)
    assert_almost_equal(mx.nd.exp(a).asnumpy(), np.exp(x), rtol=1e-5)
    assert_almost_equal(mx.nd.log(a).asnumpy(), np.log(x), rtol=1e-5)
    assert_almost_equal(mx.nd.square(a).asnumpy(), x ** 2, rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a).asnumpy(), x.sum(), rtol=1e-5)
    assert_almost_equal(
        mx.nd.sum(a, axis=1).asnumpy(), x.sum(axis=1), rtol=1e-5
    )
    assert_almost_equal(mx.nd.max(a, axis=0).asnumpy(), x.max(axis=0))
    assert_almost_equal(
        mx.nd.transpose(a).asnumpy(), x.T
    )


def test_ndarray_dot():
    rs = np.random.RandomState(1)
    x = rs.randn(4, 5).astype(np.float32)
    y = rs.randn(5, 3).astype(np.float32)
    res = mx.nd.dot(mx.nd.array(x), mx.nd.array(y))
    assert_almost_equal(res.asnumpy(), x @ y, rtol=1e-5, atol=1e-5)
    # transpose flags
    res2 = mx.nd.dot(mx.nd.array(x), mx.nd.array(y.T), transpose_b=True)
    assert_almost_equal(res2.asnumpy(), x @ y, rtol=1e-5, atol=1e-5)


def test_ndarray_concat_split():
    x = np.arange(12).reshape(3, 4).astype(np.float32)
    y = np.arange(12, 24).reshape(3, 4).astype(np.float32)
    c = mx.nd.concat(mx.nd.array(x), mx.nd.array(y), dim=0)
    assert same(c.asnumpy(), np.concatenate([x, y], axis=0))
    parts = mx.nd.split(mx.nd.array(x), num_outputs=2, axis=1)
    assert same(parts[0].asnumpy(), x[:, :2])
    assert same(parts[1].asnumpy(), x[:, 2:])


def test_ndarray_saveload():
    with tempfile.TemporaryDirectory() as td:
        fname = os.path.join(td, "nd.bin")
        arrays = [mx.nd.array(np.random.rand(3, 4)), mx.nd.ones((2,))]
        mx.nd.save(fname, arrays)
        loaded = mx.nd.load(fname)
        assert len(loaded) == 2
        for a, b in zip(arrays, loaded):
            assert same(a.asnumpy(), b.asnumpy())
        d = {"w": arrays[0], "b": arrays[1]}
        mx.nd.save(fname, d)
        loaded_d = mx.nd.load(fname)
        assert set(loaded_d) == {"w", "b"}
        assert same(loaded_d["w"].asnumpy(), arrays[0].asnumpy())


def test_ndarray_broadcast():
    a = mx.nd.ones((2, 1, 3))
    b = a.broadcast_to((2, 4, 3))
    assert b.shape == (2, 4, 3)
    assert same(b.asnumpy(), np.ones((2, 4, 3), dtype=np.float32))


def test_ndarray_wait():
    a = mx.nd.ones((10, 10))
    b = mx.nd.dot(a, a)
    b.wait_to_read()
    mx.nd.waitall()


def test_ndarray_scalar_semantics():
    a = mx.nd.array([3.5])
    assert float(a) == 3.5
    assert int(a) == 3
    with pytest.raises(Exception):
        mx.nd.ones((2,)).asscalar()


def test_onehot_encode():
    ind = mx.nd.array([1, 0, 2])
    out = mx.nd.zeros((3, 3))
    mx.nd.onehot_encode(ind, out)
    assert same(out.asnumpy(), np.eye(3, dtype=np.float32)[[1, 0, 2]])
