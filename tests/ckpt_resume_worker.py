"""Worker for the single-host kill-resume test (tests/test_checkpoint.py).

Trains a small deterministic MLP with env-driven checkpointing
(MXNET_CHECKPOINT_DIR + MXNET_CHECKPOINT_BATCH_PERIOD) so `Module.fit`
saves crash-consistent checkpoints mid-epoch. The test's first launch sets
MXNET_FI_CRASH_AT_BATCH so faultinject hard-kills the process (os._exit,
no cleanup) mid-epoch; the second launch sets MXNET_NUM_RESTARTS=1 (the
launcher convention) so the injection is disarmed, and fit must auto-resume
from the last committed checkpoint.

Prints machine-checkable lines:
  RESUME epoch=<E> batch=<B> num_update=<N>   (pre-fit view of the latest
                                               checkpoint; epoch=-1 if none)
  TRAIN-DONE acc=<float> final_update=<N>
"""

import logging
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    logging.basicConfig(level=logging.INFO, stream=sys.stdout)
    import mxnet_tpu as mx

    rng = np.random.RandomState(42)
    X = rng.randn(64, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(X, Y, batch_size=8)  # 8 batches/epoch

    ckpt_dir = os.environ["MXNET_CHECKPOINT_DIR"]
    loaded = mx.checkpoint.load_latest(ckpt_dir)
    if loaded is None:
        print("RESUME epoch=-1 batch=-1 num_update=0", flush=True)
    else:
        meta = loaded.manifest.get("optimizer") or {}
        print(f"RESUME epoch={loaded.next_epoch} batch={loaded.next_batch} "
              f"num_update={meta.get('num_update', 0)}", flush=True)

    mx.random.seed(7)
    mod.fit(
        it, num_epoch=int(os.environ.get("WORKER_NUM_EPOCH", "6")),
        initializer=mx.init.Xavier(),
        optimizer="sgd",
        optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
    )
    metric = mx.metric.Accuracy()
    acc = mod.score(it, metric)[0][1]
    final_update = mod._optimizer.num_update
    print(f"TRAIN-DONE acc={acc:.3f} final_update={final_update}",
          flush=True)


if __name__ == "__main__":
    main()
