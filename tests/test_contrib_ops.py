"""RNN fused op, Custom op, detection/vision op tests
(reference test_operator.py RNN cases, test_multibox*, custom op tests)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import operator as mxop
from mxnet_tpu.test_utils import assert_almost_equal

rs = np.random.RandomState(3)


def test_rnn_op_lstm_matches_numpy():
    T, N, C, H = 5, 3, 4, 6
    x = rs.randn(T, N, C).astype(np.float32)
    rnn = mx.sym.RNN(mx.sym.Variable("data"), mode="lstm", state_size=H,
                     num_layers=1, state_outputs=True, name="rnn")
    exe = rnn.simple_bind(ctx=mx.cpu(), data=(T, N, C))
    params = rs.randn(*exe.arg_dict["rnn_parameters"].shape).astype(np.float32) * 0.1
    exe.arg_dict["rnn_parameters"][:] = mx.nd.array(params)
    exe.forward(is_train=False, data=mx.nd.array(x))
    out, hT, cT = [o.asnumpy() for o in exe.outputs]

    m = 4 * H
    wi = params[:m * C].reshape(m, C)
    wh = params[m * C:m * C + m * H].reshape(m, H)
    bi = params[m * C + m * H:m * C + m * H + m]
    bh = params[m * C + m * H + m:]
    sig = lambda z: 1 / (1 + np.exp(-z))
    h = np.zeros((N, H)); c = np.zeros((N, H))
    for t in range(T):
        g = x[t] @ wi.T + bi + h @ wh.T + bh
        i, f, cc, o = np.split(g, 4, axis=1)
        c = sig(f) * c + sig(i) * np.tanh(cc)
        h = sig(o) * np.tanh(c)
    assert_almost_equal(out[-1], h, rtol=1e-4, atol=1e-5)
    assert_almost_equal(hT[0], h, rtol=1e-4, atol=1e-5)
    assert_almost_equal(cT[0], c, rtol=1e-4, atol=1e-5)


def test_rnn_op_bidirectional_gru():
    T, N, C, H = 4, 2, 3, 5
    rnn = mx.sym.RNN(mx.sym.Variable("data"), mode="gru", state_size=H,
                     num_layers=2, bidirectional=True, name="rnn")
    exe = rnn.simple_bind(ctx=mx.cpu(), data=(T, N, C))
    exe.forward(is_train=False, data=mx.nd.array(rs.randn(T, N, C).astype(np.float32)))
    assert exe.outputs[0].shape == (T, N, 2 * H)


def test_rnn_op_gradient():
    T, N, C, H = 3, 2, 3, 4
    rnn = mx.sym.RNN(mx.sym.Variable("data"), mode="rnn_tanh", state_size=H,
                     num_layers=1, name="rnn")
    summed = mx.sym.sum(rnn)
    arg_shapes, _, _ = summed.infer_shape(data=(T, N, C))
    location = {
        n: rs.randn(*s).astype(np.float32) * 0.5
        for n, s in zip(summed.list_arguments(), arg_shapes)
    }
    mx.test_utils.check_numeric_gradient(
        summed, location, grad_nodes=["data", "rnn_parameters"],
        rtol=0.1, atol=1e-2,
    )


def test_custom_op():
    @mxop.register("test_sq")
    class SqProp(mxop.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            class Sq(mxop.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0],
                                mx.nd.array(in_data[0].asnumpy() ** 2))

                def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                    self.assign(
                        in_grad[0], req[0],
                        mx.nd.array(2 * in_data[0].asnumpy() * out_grad[0].asnumpy()),
                    )
            return Sq()

    x = rs.randn(2, 3).astype(np.float32)
    net = mx.sym.Custom(mx.sym.Variable("x"), op_type="test_sq")
    exe = net.bind(mx.cpu(), args={"x": mx.nd.array(x)},
                   args_grad={"x": mx.nd.zeros(x.shape)})
    exe.forward(is_train=True)
    assert_almost_equal(exe.outputs[0].asnumpy(), x ** 2, rtol=1e-5)
    exe.backward(mx.nd.ones(x.shape))
    assert_almost_equal(exe.grad_dict["x"].asnumpy(), 2 * x, rtol=1e-5)


def test_multibox_prior():
    data = mx.nd.zeros((1, 3, 4, 4))
    anchors = mx.nd.MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0, 2.0))
    assert anchors.shape == (1, 4 * 4 * 2, 4)
    a = anchors.asnumpy()[0]
    # first anchor at cell (0,0): center (0.125, 0.125), size 0.5 → half 0.25
    assert_almost_equal(a[0], [0.125 - 0.25, 0.125 - 0.25, 0.375, 0.375],
                        rtol=1e-5, atol=1e-6)
    # widths of ratio-2 anchor: w = 0.5*sqrt(2)/2
    w2 = a[1][2] - a[1][0]
    assert abs(w2 - 0.5 * np.sqrt(2)) < 1e-5


def test_multibox_target_matching():
    # one anchor exactly on the gt, one far away
    anchors = mx.nd.array([[[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]]])
    label = mx.nd.array([[[1.0, 0.1, 0.1, 0.4, 0.4]]])  # class 1 at first anchor
    cls_pred = mx.nd.zeros((1, 3, 2))
    loc_t, loc_mask, cls_t = mx.nd.MultiBoxTarget(anchors, label, cls_pred)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 2.0  # class 1 + 1
    assert ct[1] == 0.0  # background
    lm = loc_mask.asnumpy()[0]
    assert lm[:4].sum() == 4 and lm[4:].sum() == 0
    # matched anchor == gt → zero offsets
    assert_almost_equal(loc_t.asnumpy()[0][:4], np.zeros(4), atol=1e-5)


def test_multibox_detection_decode_nms():
    anchors = mx.nd.array([[[0.1, 0.1, 0.4, 0.4], [0.12, 0.12, 0.42, 0.42],
                            [0.6, 0.6, 0.9, 0.9]]])
    # class scores: anchor0/1 strongly class1 (overlapping), anchor2 class2
    cls_prob = mx.nd.array([[[0.01, 0.01, 0.2],   # background
                             [0.9, 0.8, 0.1],     # class 0 (fg)
                             [0.09, 0.19, 0.7]]])  # class 1 (fg)
    loc_pred = mx.nd.zeros((1, 12))
    out = mx.nd.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                  nms_threshold=0.5).asnumpy()[0]
    # anchor1 should be suppressed by anchor0 (same class, IOU > 0.5)
    assert out[0][0] == 0.0 and out[0][1] > 0.85
    assert out[1][0] == -1.0  # suppressed
    assert out[2][0] == 1.0


def test_roi_pooling():
    data = mx.nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = mx.nd.array([[0, 0, 0, 3, 3]])  # whole image
    out = mx.nd.ROIPooling(data, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    assert_almost_equal(out.asnumpy()[0, 0], [[5, 7], [13, 15]])


def test_correlation_identity():
    a = mx.nd.array(rs.randn(1, 2, 4, 4).astype(np.float32))
    out = mx.nd.Correlation(a, a, max_displacement=1, pad_size=1)
    assert out.shape == (1, 9, 4, 4)
    # zero-displacement channel (index 4) = mean over channels of a*a
    expected = (a.asnumpy() ** 2).mean(axis=1)
    assert_almost_equal(out.asnumpy()[:, 4], expected, rtol=1e-4, atol=1e-5)


def test_ctc_loss_uniform():
    act = np.zeros((2, 1, 3), np.float32)
    lbl = np.array([[1, 0]], np.float32)
    loss = mx.test_utils.simple_forward(
        mx.sym.ctc_loss(mx.sym.Variable("a"), mx.sym.Variable("l")),
        a=act, l=lbl,
    )
    assert_almost_equal(loss, [-np.log(3 / 9)], rtol=1e-4)


def test_bilinear_sampler_identity():
    d = rs.randn(1, 2, 5, 5).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                         indexing="ij")
    grid = np.stack([xs, ys])[None].astype(np.float32)
    out = mx.test_utils.simple_forward(
        mx.sym.BilinearSampler(mx.sym.Variable("d"), mx.sym.Variable("g")),
        d=d, g=grid,
    )
    assert_almost_equal(out, d, rtol=1e-4, atol=1e-5)


def test_fft_roundtrip():
    x = rs.randn(2, 8).astype(np.float32)
    f = mx.nd.fft(mx.nd.array(x))
    assert f.shape == (2, 16)
    back = mx.nd.ifft(f)
    assert_almost_equal(back.asnumpy(), x, rtol=1e-4, atol=1e-5)


def test_quantize_roundtrip():
    x = rs.uniform(-1, 1, (3, 4)).astype(np.float32)
    q, mn, mx_ = mx.nd.quantize(
        mx.nd.array(x), mx.nd.array([-1.0]), mx.nd.array([1.0])
    )
    assert q.dtype == np.int8
    back = mx.nd.dequantize(q, mn, mx_)
    assert_almost_equal(back.asnumpy(), x, atol=0.02)
