"""Executor tests (reference test_executor.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import assert_almost_equal

rs = np.random.RandomState(11)


def test_bind_forward_backward():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a + b * 2
    x = rs.randn(3, 4).astype(np.float32)
    y = rs.randn(3, 4).astype(np.float32)
    exe = c.bind(
        mx.cpu(), args={"a": mx.nd.array(x), "b": mx.nd.array(y)},
        args_grad={"a": mx.nd.zeros(x.shape), "b": mx.nd.zeros(y.shape)},
    )
    exe.forward(is_train=True)
    assert_almost_equal(exe.outputs[0].asnumpy(), x + 2 * y)
    og = rs.randn(3, 4).astype(np.float32)
    exe.backward(mx.nd.array(og))
    assert_almost_equal(exe.grad_dict["a"].asnumpy(), og)
    assert_almost_equal(exe.grad_dict["b"].asnumpy(), 2 * og)


def test_simple_bind_allocates():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=6, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(4, 8))
    assert exe.arg_dict["fc_weight"].shape == (6, 8)
    assert exe.arg_dict["fc_bias"].shape == (6,)
    assert exe.grad_dict["fc_weight"].shape == (6, 8)
    exe.forward(is_train=False)
    assert exe.outputs[0].shape == (4, 6)


def test_forward_kwargs_update():
    net = mx.sym.square(mx.sym.Variable("x"))
    exe = net.simple_bind(ctx=mx.cpu(), x=(2, 2), grad_req="null")
    exe.forward(x=mx.nd.array([[1, 2], [3, 4]]))
    assert_almost_equal(exe.outputs[0].asnumpy(), [[1, 4], [9, 16]])
    exe.forward(x=mx.nd.array([[2, 2], [2, 2]]))
    assert_almost_equal(exe.outputs[0].asnumpy(), [[4, 4], [4, 4]])


def test_outputs_persistent_handles():
    net = mx.sym.Variable("x") * 2
    exe = net.simple_bind(ctx=mx.cpu(), x=(2,), grad_req="null")
    exe.forward(x=mx.nd.array([1.0, 2.0]))
    out = exe.outputs[0]
    assert_almost_equal(out.asnumpy(), [2, 4])
    exe.forward(x=mx.nd.array([5.0, 6.0]))
    # same handle updates in place (reference persistent outputs)
    assert_almost_equal(out.asnumpy(), [10, 12])


def test_copy_params_from():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    w = rs.randn(3, 4).astype(np.float32)
    exe.copy_params_from({"fc_weight": mx.nd.array(w)}, allow_extra_params=True)
    assert_almost_equal(exe.arg_dict["fc_weight"].asnumpy(), w)
    with pytest.raises(MXNetError):
        exe.copy_params_from({"nonexistent": mx.nd.zeros((1,))})


def test_monitor_callback_interpret_mode():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.Activation(net, act_type="relu", name="act")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    seen = []
    exe.set_monitor_callback(lambda name, arr: seen.append(name))
    exe.forward(is_train=False, data=mx.nd.ones((2, 4)))
    assert "fc_output" in seen
    assert "act_output" in seen


def test_executor_reshape():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(4, 8))
    w = exe.arg_dict["fc_weight"]
    exe2 = exe.reshape(data=(16, 8))
    assert exe2.arg_dict["data"].shape == (16, 8)
    # parameters are shared, not copied
    assert exe2.arg_dict["fc_weight"] is w
    exe2.forward(is_train=False, data=mx.nd.ones((16, 8)))
    assert exe2.outputs[0].shape == (16, 3)


def test_rng_determinism_per_step():
    net = mx.sym.Dropout(mx.sym.Variable("x"), p=0.5)
    exe = net.simple_bind(ctx=mx.cpu(), x=(50, 50), grad_req="null")
    exe.forward(is_train=True, x=mx.nd.ones((50, 50)))
    m1 = exe.outputs[0].asnumpy()
    exe.forward(is_train=True, x=mx.nd.ones((50, 50)))
    m2 = exe.outputs[0].asnumpy()
    assert not np.array_equal(m1, m2)  # different step → different mask


def test_multi_output_executor():
    x = mx.sym.Variable("x")
    parts = mx.sym.SliceChannel(x, num_outputs=2, name="sc")
    grouped = mx.sym.Group([parts[0] * 2, parts[1] + 1])
    exe = grouped.simple_bind(ctx=mx.cpu(), x=(2, 4), grad_req="null")
    exe.forward(x=mx.nd.array([[1, 2, 3, 4], [5, 6, 7, 8]]))
    assert_almost_equal(exe.outputs[0].asnumpy(), [[2, 4], [10, 12]])
    assert_almost_equal(exe.outputs[1].asnumpy(), [[4, 5], [8, 9]])


def test_debug_str_and_partial_forward():
    """Executor introspection (reference DebugStr + PartialForward)."""
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc1"),
        act_type="relu", name="act1",
    )
    net = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    exe = net.simple_bind(mx.cpu(), data=(3, 5))
    plan = exe.debug_str()
    assert "fc1" in plan and "fc2" in plan and "FullyConnected" in plan
    assert "Total" in plan

    rng = np.random.RandomState(0)
    x = rng.randn(3, 5).astype(np.float32)
    w1 = rng.randn(4, 5).astype(np.float32)
    exe.arg_dict["fc1_weight"][:] = mx.nd.array(w1)
    exe.arg_dict["fc1_bias"][:] = mx.nd.zeros((4,))
    # first op node only: the fc1 pre-activation
    outs = exe.partial_forward(num_nodes=1, data=mx.nd.array(x))
    np.testing.assert_allclose(outs[0].asnumpy(), x.dot(w1.T), rtol=1e-5)
    # two nodes: relu applied
    outs = exe.partial_forward(num_nodes=2, data=mx.nd.array(x))
    np.testing.assert_allclose(
        outs[0].asnumpy(), np.maximum(x.dot(w1.T), 0), rtol=1e-5
    )


def test_reshape_uses_lazy_placeholders():
    """Bucketing-style reshape must not allocate fresh input/grad buffers
    per bucket: mismatched-shape entries are lazy placeholders that the
    per-batch bind overwrites without ever materialising (the reference
    bounds bucket memory with the shared data_pool_,
    graph_executor.cc:813-817)."""
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc"),
        name="softmax")
    exe = net.simple_bind(mx.cpu(), data=(8, 6), softmax_label=(8,))
    exe.arg_dict["fc_weight"][:] = np.ones((4, 6), np.float32)
    exe2 = exe.reshape(data=(2, 6), softmax_label=(2,))
    data2 = exe2.arg_dict["data"]
    assert data2._d is None, "placeholder materialised eagerly"
    assert data2.shape == (2, 6)          # metadata without allocation
    assert str(data2.dtype) == "float32"
    assert data2._d is None, "shape/dtype query allocated the placeholder"
    # params are SHARED, not copied
    assert exe2.arg_dict["fc_weight"]._d is exe.arg_dict["fc_weight"]._d
    # the normal flow binds fresh data; the placeholder must never fire
    out = exe2.forward(
        is_train=False, data=np.ones((2, 6), np.float32),
        softmax_label=np.zeros(2, np.float32),
    )[0].asnumpy()
    assert out.shape == (2, 4)
    # reading an UNBOUND placeholder still works (materialises zeros)
    exe3 = exe.reshape(data=(3, 6), softmax_label=(3,))
    assert np.all(exe3.grad_dict["fc_weight"].asnumpy() == 0) \
        if exe3.grad_dict.get("fc_weight") is not None else True


def test_nonuniform_workload_warns():
    import warnings

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        DataParallelExecutorGroup(
            net, [mx.cpu(0), mx.cpu(1)], workload=[1, 3],
            data_shapes=[("data", (16, 4))],
            label_shapes=[("softmax_label", (16,))],
            param_names=[n for n in net.list_arguments()
                         if n not in ("data", "softmax_label")],
            for_training=True, inputs_need_grad=False,
        )
    assert any("workload" in str(x.message) for x in w), \
        [str(x.message) for x in w]


def test_xla_flags_reach_compile_options_and_digests(monkeypatch):
    """MXNET_XLA_FLAGS threads into the per-executable compiler options
    (typed: bools/ints coerced — XLA's debug-option overrides are typed)
    AND into the AOT digest/fingerprint, so a persisted executable never
    serves a program compiled under different flags."""
    from mxnet_tpu import aot
    from mxnet_tpu.executor import _compiler_options, _parse_xla_flag

    monkeypatch.delenv("MXNET_XLA_FLAGS", raising=False)
    assert _compiler_options(mx.cpu()) is None  # empty -> jax defaults
    base_digest = aot.digest("probe")

    monkeypatch.setenv(
        "MXNET_XLA_FLAGS",
        "xla_cpu_enable_fast_math=true, xla_force_host_platform_device_count=2,"
        "xla_gpu_autotune_level=0.5,xla_dump_to=/tmp/x")
    opts = _compiler_options(mx.cpu())
    assert opts == {"xla_cpu_enable_fast_math": True,
                    "xla_force_host_platform_device_count": 2,
                    "xla_gpu_autotune_level": 0.5,
                    "xla_dump_to": "/tmp/x"}
    assert _parse_xla_flag("false") is False
    # different flags => different AOT digest for the SAME program
    assert aot.digest("probe") != base_digest
