"""Pragmas that must themselves be findings: missing reason, unknown
check name."""
import os


def peek():
    return os.environ.get("MXNET_TRAIN_WINDOW")  # graftlint: allow=env-registry()


def poke():
    return os.environ.get("MXNET_PROC_ID")  # graftlint: allow=no-such-check(because)
