"""Clean counterpart: every MXNET_* read routes through the registry;
foreign variables are outside its jurisdiction."""
import os

from mxnet_tpu import env


def windows_enabled():
    return env.get("MXNET_TRAIN_WINDOW") != ""


def has_rank():
    return env.raw("MXNET_PROC_ID") is not None


def jax_platform():
    return os.environ.get("JAX_PLATFORMS", "")   # fine: not an MXNET_* var
