"""Seeded transcription tells."""


def get_interals(symbol):           # BAD: the reference's typo, preserved
    interals = symbol.get_internals()
    return interals.list_outputs()


def recieve_frame(sock, lenght):    # BAD: two more known tells
    return sock.recv(lenght)
