"""Seeded host-sync violations: a declared hot path that syncs per
batch, and a sync reachable only through TWO call hops from the root —
the reachability engine must walk the chain and print it."""


# graftlint: hotpath
def serve_batch(batcher, batch):
    out = batcher.run(batch)
    host = out.asnumpy()          # BAD: d2h sync on the request path
    out.wait_to_read()            # BAD: execution fence per batch
    return host


# graftlint: hotpath
def pump(iterator, sink):
    while iterator.more():
        step(iterator, sink)


def step(iterator, sink):
    sink.push(fetch_metrics(iterator))


def fetch_metrics(it):
    return it.metric.asnumpy()    # BAD: two call hops below the hot root