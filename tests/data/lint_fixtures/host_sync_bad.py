"""Seeded host-sync violation: a declared hot path that syncs per batch."""


# graftlint: hotpath
def serve_batch(batcher, batch):
    out = batcher.run(batch)
    host = out.asnumpy()          # BAD: d2h sync on the request path
    out.wait_to_read()            # BAD: execution fence per batch
    return host
