"""Seeded env-registry violations: raw MXNET_* environ access."""
import os


def windows_enabled():
    return os.environ.get("MXNET_TRAIN_WINDOW", "") != ""   # BAD: raw read


def force_windows(k):
    os.environ["MXNET_TRAIN_WINDOW"] = str(k)               # BAD: raw write


def has_rank():
    return "MXNET_PROC_ID" in os.environ                    # BAD: raw probe


def sniff(name):
    return os.environ.get(name)        # BAD: dynamic, unauditable key
