"""A violation carrying a line pragma WITH a reason, plus a file-wide
allowance: both forms of the suppression contract."""
# graftlint: allow=typos(fixture exercising the file-wide allowance form)
import os


def get_interals():
    return None


def peek():
    return os.environ.get("MXNET_TRAIN_WINDOW")  # graftlint: allow=env-registry(fixture: deliberate raw read exercising the line-pragma form)
