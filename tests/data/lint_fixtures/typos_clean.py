"""Clean counterpart."""


def get_internals(symbol):
    internals = symbol.get_internals()
    return internals.list_outputs()


def receive_frame(sock, length):
    return sock.recv(length)
