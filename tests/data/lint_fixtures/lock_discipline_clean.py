"""Clean counterpart: one global acquisition order (also across the two
classes and through call edges), every shared-field write guarded, the
run lock held only around the swap itself, waits happen after release."""
import threading


class Pool:
    def __init__(self):
        self._health = threading.Lock()
        self._route = threading.Lock()
        self.run_lock = threading.Lock()
        self.version = 0

    def mark_down(self, rid):
        with self._health:
            with self._route:
                self.version += 1

    def pick(self):
        with self._health:          # same order everywhere
            with self._route:
                return self.version

    def reload(self, v):
        with self._health:
            with self._route:
                self.version = v

    def dispatch(self, fut, model, batch):
        out = model.forward(batch)
        fut.set_result(out)
        with self.run_lock:
            self._swap()

    def _swap(self):
        pass


# --- the two classes keep ONE order through call edges: journal before
# --- sink, in both directions of the collaboration

class Journal:
    def __init__(self):
        self._log_lock = threading.Lock()

    def commit(self, sink, item):
        with self._log_lock:
            sink.record_stat(item)

    def log_locked(self):
        with self._log_lock:
            pass


class StatSink:
    def __init__(self):
        self._stat_lock = threading.Lock()

    def record_stat(self, item):
        with self._stat_lock:
            pass

    def snapshot(self, journal):
        journal.log_locked()        # take C OUTSIDE D, then D alone
        with self._stat_lock:
            pass


# --- wait first, lock second

class Gate:
    def __init__(self):
        self._g_lock = threading.Lock()
        self._ready = threading.Event()

    def _wait_ready(self):
        self._ready.wait()

    def sync_in(self):
        self._wait_ready()          # wait with nothing held
        with self._g_lock:
            return True
