"""Clean counterpart: one global acquisition order, every shared-field
write guarded, the run lock held only around the swap itself."""
import threading


class Pool:
    def __init__(self):
        self._health = threading.Lock()
        self._route = threading.Lock()
        self.run_lock = threading.Lock()
        self.version = 0

    def mark_down(self, rid):
        with self._health:
            with self._route:
                self.version += 1

    def pick(self):
        with self._health:          # same order everywhere
            with self._route:
                return self.version

    def reload(self, v):
        with self._health:
            with self._route:
                self.version = v

    def dispatch(self, fut, model, batch):
        out = model.forward(batch)
        fut.set_result(out)
        with self.run_lock:
            self._swap()

    def _swap(self):
        pass
