"""Seeded trace-purity violations inside a jitted function and a loop
body handed to lax.fori_loop."""
import os
import random
import time

import jax

_seen = []


@jax.jit
def impure_step(x):
    t = time.time()                    # BAD: wall-clock frozen into trace
    noise = random.random()            # BAD: host RNG draw baked in
    if os.environ.get("MXNET_FOO"):    # BAD: config pinned at trace time
        x = x + 1
    print("tracing", x)                # BAD: trace-time-only effect
    _seen.append(x)
    return x * t + noise


def window(x0):
    def body(i, carry):
        _seen[0] = carry               # BAD: mutates closed-over state
        return carry + i

    return jax.lax.fori_loop(0, 4, body, x0)
