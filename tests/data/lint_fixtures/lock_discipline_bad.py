"""Seeded lock-discipline violations: ABBA cycle, mixed guarded/unguarded
mutation, blocking work + future resolution under the run lock."""
import threading


class Pool:
    def __init__(self):
        self._health = threading.Lock()
        self._route = threading.Lock()
        self.run_lock = threading.Lock()
        self.version = 0

    def mark_down(self, rid):
        with self._health:          # A then B
            with self._route:
                self.version += 1

    def pick(self):
        with self._route:           # B then A: ABBA cycle
            with self._health:
                return self.version

    def reload(self, v):
        self.version = v            # BAD: same field written lock-free

    def dispatch(self, fut, model, batch):
        with self.run_lock:
            out = model.forward(batch)   # BAD: device call under run lock
            fut.set_result(out)          # BAD: client callback under lock


class AsyncWriter:
    def __init__(self):
        self._writer_lock = threading.Condition()
        self._pending = None

    def submit(self, snap, path):
        with self._writer_lock:
            with open(path, "wb") as f:     # BAD: I/O under hand-off lock
                f.write(snap)               # BAD: I/O under hand-off lock
            self._pending = snap
