"""Seeded lock-discipline violations: same-class ABBA cycle, mixed
guarded/unguarded mutation, blocking work + future resolution under the
run lock, a CROSS-CLASS ABBA whose two halves only meet through call
edges, and blocking Event.wait hidden one call below the lock."""
import threading


class Pool:
    def __init__(self):
        self._health = threading.Lock()
        self._route = threading.Lock()
        self.run_lock = threading.Lock()
        self.version = 0

    def mark_down(self, rid):
        with self._health:          # A then B
            with self._route:
                self.version += 1

    def pick(self):
        with self._route:           # B then A: ABBA cycle
            with self._health:
                return self.version

    def reload(self, v):
        self.version = v            # BAD: same field written lock-free

    def dispatch(self, fut, model, batch):
        with self.run_lock:
            out = model.forward(batch)   # BAD: device call under run lock
            fut.set_result(out)          # BAD: client callback under lock


class AsyncWriter:
    def __init__(self):
        self._writer_lock = threading.Condition()
        self._pending = None

    def submit(self, snap, path):
        with self._writer_lock:
            with open(path, "wb") as f:     # BAD: I/O under hand-off lock
                f.write(snap)               # BAD: I/O under hand-off lock
            self._pending = snap


# --- cross-class ABBA: neither class alone shows a cycle; the lock sets
# --- only collide once they propagate through the two call edges

class Journal:
    def __init__(self):
        self._log_lock = threading.Lock()

    def commit(self, sink, item):
        with self._log_lock:        # C held...
            sink.record_stat(item)  # ...then D acquired inside the callee

    def log_locked(self):
        with self._log_lock:
            pass


class StatSink:
    def __init__(self):
        self._stat_lock = threading.Lock()

    def record_stat(self, item):
        with self._stat_lock:
            pass

    def snapshot(self, journal):
        with self._stat_lock:       # D held...
            journal.log_locked()    # ...then C: cycle spans both classes


# --- blocking wait one call below the lock

class Gate:
    def __init__(self):
        self._g_lock = threading.Lock()
        self._ready = threading.Event()

    def _wait_ready(self):
        self._ready.wait()

    def sync_in(self):
        with self._g_lock:
            self._wait_ready()      # BAD: Event.wait while the lock is
            return True             # held — hidden a call down
