"""Seeded telemetry-catalog violations: dynamic + unconventional names."""
from mxnet_tpu import telemetry as _tm


def record(op, n):
    _tm.counter(f"serving.{op}").inc(n)       # BAD: dynamic name
    _tm.counter("TotalRequests").inc()        # BAD: not sub.system.name
    _tm.gauge("queue").set(n)                 # BAD: no subsystem segment
