"""Clean counterpart: literal, convention-shaped instrument names."""
from mxnet_tpu import telemetry as _tm


def record(n):
    _tm.counter("serving.request").inc(n)
    _tm.gauge("serving.queue_depth").set(n)
    with _tm.span("serving.infer", valid=n):
        pass
