"""Clean counterpart: the hot path stays async; the drain point is not
declared hot (and a deliberate fence would carry a line pragma)."""


# graftlint: hotpath
def serve_batch(batcher, batch):
    return batcher.run(batch)


def epoch_drain(metric):
    # not a hot path: epoch-boundary drains may sync
    return metric.get().asnumpy()
