"""Clean counterpart: the hot path stays async through every hop; the
drain point is either unreachable from a root or sits behind a call-site
pragma that declares the cold boundary."""


# graftlint: hotpath
def serve_batch(batcher, batch):
    return batcher.run(batch)


# graftlint: hotpath
def pump(iterator, sink):
    while iterator.more():
        step(iterator, sink)


def step(iterator, sink):
    sink.push(stage(iterator))


def stage(it):
    return it.metric              # device handle stays on device


# graftlint: hotpath
def run_epoch(iterator, manager):
    pump(iterator, manager.sink)
    drain(manager)  # graftlint: allow=host-sync(epoch-boundary metric drain — deliberate cold boundary, one pragma covers the subtree)


def drain(manager):
    # reachable ONLY through the pragma-cut edge above: not reported
    return manager.metric.asnumpy()


def epoch_drain(metric):
    # not reachable from any root: epoch-boundary drains may sync
    return metric.get().asnumpy()
