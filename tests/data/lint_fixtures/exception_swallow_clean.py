"""Fixture twin: every catch-all here is observable or out of scope —
the exception-swallow checker must stay silent."""

import logging

_LOG = logging.getLogger(__name__)


def decode_worker(pool, telemetry):
    while not pool.stopped:
        try:
            pool.step()
        except Exception:
            telemetry.counter("worker_crash").inc()
            raise


def supervision_loop(replicas):
    while True:
        for rep in replicas:
            try:
                rep.health_check()
            except Exception as exc:
                _LOG.warning("health check failed: %s", exc)


def hand_off(chan, results):
    while True:
        try:
            results.append(chan.recv())
        except BaseException as exc:
            results.append(exc)  # delivered to the consumer, not dropped
            return


def narrow_retry(chan):
    while True:
        try:
            return chan.recv()
        except TimeoutError:
            continue  # specific exception: out of scope by design


def best_effort_close(handle):
    # one-shot cleanup outside any loop: out of scope
    try:
        handle.close()
    except Exception:
        pass
