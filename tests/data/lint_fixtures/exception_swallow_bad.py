"""Fixture: silent catch-alls inside worker loops — every handler here
must be flagged by the exception-swallow checker."""

import time


def decode_worker(pool):
    while not pool.stopped:
        try:
            pool.step()
        except Exception:
            pass  # crash becomes a silent hang


def supervision_loop(replicas):
    while True:
        for rep in replicas:
            try:
                rep.health_check()
            except:  # noqa: E722 — the bare form is the point
                continue


def retry_forever(chan):
    while True:
        try:
            return chan.recv()
        except BaseException:
            time.sleep(0.01)  # backoff alone is still a swallow
