"""Clean counterpart: pure traced bodies; jax.random is allowed, and the
host effects happen OUTSIDE the traced function."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def pure_step(x, key):
    noise = jax.random.normal(key, x.shape)   # fine: traced RNG
    return x * 2.0 + noise


def window(x0):
    def body(i, carry):
        local = carry + i                     # locals are fine
        return local

    return jax.lax.fori_loop(0, 4, body, x0)


def timed_dispatch(x, key):
    tic = time.time()                         # fine: outside the trace
    out = pure_step(x, key)
    return out, time.time() - tic
