"""Elastic sharded checkpoints (format v2): mesh-aware save, cross-topology
restore, resume consensus, async bounded-stall writes, and the
kill-during-save chaos matrix.

The cross-topology oracle is an uninterrupted run: params + optimizer
state (momentum) saved under one GraftMesh must restore under a DIFFERENT
mesh — re-staged pipelines included — and training forward from the
restore must land exactly where the uninterrupted source run lands.
"""

import contextlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint as ckpt
from mxnet_tpu import parallel
from mxnet_tpu import telemetry as tm
from mxnet_tpu.parallel.mesh import GraftMesh
from mxnet_tpu.test_utils import assert_almost_equal

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

BATCH, DIM, HID, NCLS = 16, 8, 12, 5


# --------------------------------------------------------------------------
# one logical chain (st0_fc -> st1_fc -> st2_fc -> st_last_fc), staged
# three ways: 4 pipeline stages, 2 pipeline stages, or one plain module.
# Param names are identical across stagings — that's what makes a
# checkpoint written under one topology meaningful under another.
# --------------------------------------------------------------------------

def _four_stage_syms():
    syms = []
    for i in range(3):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=HID, name=f"st{i}_fc")
        syms.append(mx.sym.Activation(fc, act_type="tanh",
                                      name=f"st{i}_act"))
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=NCLS, name="st_last_fc")
    syms.append(mx.sym.SoftmaxOutput(fc, name="softmax"))
    return syms


def _two_stage_syms():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=HID, name="st0_fc")
    h = mx.sym.Activation(h, act_type="tanh", name="st0_act")
    h = mx.sym.FullyConnected(h, num_hidden=HID, name="st1_fc")
    s0 = mx.sym.Activation(h, act_type="tanh", name="st1_act")
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=HID, name="st2_fc")
    h = mx.sym.Activation(h, act_type="tanh", name="st2_act")
    h = mx.sym.FullyConnected(h, num_hidden=NCLS, name="st_last_fc")
    s1 = mx.sym.SoftmaxOutput(h, name="softmax")
    return [s0, s1]


def _chain_sym():
    h = mx.sym.Variable("data")
    for i in range(3):
        h = mx.sym.FullyConnected(h, num_hidden=HID, name=f"st{i}_fc")
        h = mx.sym.Activation(h, act_type="tanh", name=f"st{i}_act")
    h = mx.sym.FullyConnected(h, num_hidden=NCLS, name="st_last_fc")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _seq_from_syms(mesh, syms):
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms[:-1]):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    seq.add(mx.mod.Module(syms[-1], data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    return seq


def _plain_module(mesh=None):
    mod = mx.mod.Module(_chain_sym(), context=mx.cpu())
    cm = parallel.with_mesh(mesh) if mesh is not None \
        else contextlib.nullcontext()
    with cm:
        mod.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(initializer=mx.init.Uniform(0.5))
    return mod


def _build_on(spec):
    """(module, mesh) staged appropriately for `spec` (None = single dev)."""
    if spec is None:
        return _plain_module(), None
    gm = GraftMesh.from_spec(spec)
    if "pp4" in spec:
        return _seq_from_syms(gm, _four_stage_syms()), gm
    if "pp2" in spec:
        return _seq_from_syms(gm, _two_stage_syms()), gm
    return _plain_module(gm), gm


_OPT = {"learning_rate": 0.1, "momentum": 0.9}


def _batch(rs):
    data = mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))
    label = mx.nd.array(rs.randint(0, NCLS, (BATCH,)).astype(np.float32))
    return mx.io.DataBatch(data=[data], label=[label])


def _train(mod, batches):
    for b in batches:
        mod.forward(b, is_train=True)
        mod.backward()
        mod.update()


def _params_numpy(mod):
    args, auxs = mod.get_params()
    return ({k: v.asnumpy() for k, v in args.items()},
            {k: v.asnumpy() for k, v in auxs.items()})


def _save_from(mod, mesh, cfg):
    mgr = ckpt.CheckpointManager(cfg, module=mod)
    cm = parallel.with_mesh(mesh) if mesh is not None \
        else contextlib.nullcontext()
    with cm:
        return mgr.save(next_epoch=1, next_batch=0)


# --------------------------------------------------------------------------
# cross-topology resume parity
# --------------------------------------------------------------------------

@pytest.mark.parametrize("target", ["dp4,pp2", "dp2,tp2,pp2", "dp8", None],
                         ids=["dp4pp2", "dp2tp2pp2", "dp8", "single"])
def test_cross_topology_resume_parity_from_composed(tmp_path, target):
    """A checkpoint written under dp2,pp4 (4-stage packed pipeline)
    restores — params AND momentum — under re-staged 2-stage pipelines,
    pure-dp, and a single device; training forward from the restore
    matches the uninterrupted source run."""
    rs = np.random.RandomState(21)
    batches = [_batch(rs) for _ in range(4)]
    src, gm_src = _build_on("dp2,pp4")
    src.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    _train(src, batches[:2])
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    path = _save_from(src, gm_src, cfg)
    with open(os.path.join(path, "manifest.json")) as f:
        m = json.load(f)
    assert m["format"] == 2
    assert m["mesh"]["spec"] == "dp2,pp4"
    assert m["params"]["st0_fc_weight"]["kind"] == "arg"
    # the 4-stage packing wrote real per-stage slice metadata
    assert m["stage_slices"] is not None
    assert m["stage_slices"]["st_last_fc_weight"]["stage"] == 3

    # uninterrupted oracle: the source keeps training
    _train(src, batches[2:])
    oracle_args, _ = _params_numpy(src)

    tgt, gm_tgt = _build_on(target)
    tgt.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    loaded = ckpt.load_latest(cfg.dir)
    assert loaded is not None
    assert loaded.opt_states_by_name, "v2 restores optimizer state by name"
    mgr = ckpt.CheckpointManager(cfg, module=tgt)
    mgr.restore(loaded)
    _train(tgt, batches[2:])
    got_args, _ = _params_numpy(tgt)
    assert set(oracle_args) == set(got_args)
    for n in oracle_args:
        assert_almost_equal(got_args[n], oracle_args[n],
                            rtol=1e-4, atol=1e-5, names=(f"tgt:{n}", n))


def test_single_device_checkpoint_resumes_on_composed_mesh(tmp_path):
    """The other direction: written on one device, restored into a
    dp2,pp4 packed pipeline (params re-place + re-pack; momentum follows
    by name across the module split)."""
    rs = np.random.RandomState(33)
    batches = [_batch(rs) for _ in range(4)]
    src, _ = _build_on(None)
    src.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    _train(src, batches[:2])
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    _save_from(src, None, cfg)
    _train(src, batches[2:])
    oracle_args, _ = _params_numpy(src)

    tgt, _ = _build_on("dp2,pp4")
    tgt.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    loaded = ckpt.load_latest(cfg.dir)
    mgr = ckpt.CheckpointManager(cfg, module=tgt)
    mgr.restore(loaded)
    _train(tgt, batches[2:])
    got_args, _ = _params_numpy(tgt)
    for n in oracle_args:
        assert_almost_equal(got_args[n], oracle_args[n],
                            rtol=1e-4, atol=1e-5, names=(f"pp:{n}", n))


def test_packed_stage_rows_roundtrip(tmp_path):
    """Packed GPipe rows round-trip through the elastic loader: the rows
    rebuilt from restored child executors equal the rows the source held
    at save time."""
    rs = np.random.RandomState(5)
    src, gm = _build_on("dp2,pp4")
    src.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    src._pp_engine.retain_packed = True
    b = _batch(rs)
    _train(src, [b])
    src.forward(b, is_train=False)  # repack from the trained executors
    before = {dt: np.asarray(v) for dt, v in
              src._pp_engine._packed_params.items()}
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    _save_from(src, gm, cfg)

    tgt, _ = _build_on("dp2,pp4")
    mgr = ckpt.CheckpointManager(cfg, module=tgt)
    loaded = ckpt.load_latest(cfg.dir)
    mgr.restore(loaded)
    tgt.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    tgt._pp_engine.retain_packed = True
    tgt.forward(b, is_train=False)
    after = {dt: np.asarray(v) for dt, v in
             tgt._pp_engine._packed_params.items()}
    assert set(before) == set(after)
    for dt in before:
        assert_almost_equal(after[dt], before[dt], rtol=1e-6, atol=1e-7,
                            names=(f"restored:{dt}", f"saved:{dt}"))


# --------------------------------------------------------------------------
# format / loader mechanics
# --------------------------------------------------------------------------

def test_v1_format_directory_still_loads(tmp_path):
    """Backward compatibility: a format-1 directory (replicated single
    params file) loads through the v1 path untouched."""
    d = tmp_path / "ckpts"
    c = d / "ckpt-e00001-b00000000"
    os.makedirs(c)
    w = np.arange(20, dtype=np.float32).reshape(4, 5)
    s = np.ones(3, np.float32)
    mx.nd.save(str(c / "params"),
               {"arg:w": mx.nd.array(w), "aux:s": mx.nd.array(s)})
    files = {"params": {"sha256": ckpt.sha256_file(str(c / "params")),
                        "bytes": os.path.getsize(str(c / "params"))}}
    manifest = {"format": 1, "next_epoch": 1, "next_batch": 0,
                "epoch": 0, "nbatch": None, "files": files,
                "rng_key": None, "optimizer": None, "env": None}
    with open(c / "manifest.json", "w") as f:
        json.dump(manifest, f)
    (d / "LATEST").write_text("ckpt-e00001-b00000000\n")

    loaded = ckpt.load_latest(str(d))
    assert loaded is not None and loaded.manifest["format"] == 1
    np.testing.assert_array_equal(loaded.arg_params["w"].asnumpy(), w)
    np.testing.assert_array_equal(loaded.aux_params["s"].asnumpy(), s)
    assert loaded.opt_states_by_name is None
    assert loaded.next_epoch == 1


def test_stale_latest_pointer_is_ignored(tmp_path):
    """A crash between commit-rename and the LATEST update leaves LATEST
    stale; the loader must still find the newest valid commit (names are
    ordered, the pointer is only a hint)."""
    rs = np.random.RandomState(2)
    mod, _ = _build_on(None)
    mod.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    mgr = ckpt.CheckpointManager(cfg, module=mod)
    mgr.save(next_epoch=1, next_batch=0)
    _train(mod, [_batch(rs)])
    mgr.save(next_epoch=2, next_batch=0)
    # simulate the mid-LATEST torn state
    (tmp_path / "ckpts" / "LATEST").write_text("ckpt-e00001-b00000000\n")
    loaded = ckpt.load_latest(cfg.dir)
    assert loaded.next_epoch == 2


def test_shard_coverage_gap_is_corrupt(tmp_path):
    """A manifest whose shard pieces don't cover a parameter is rejected
    (geometric check, before any array maths)."""
    mod, _ = _build_on(None)
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    mgr = ckpt.CheckpointManager(cfg, module=mod)
    path = mgr.save(next_epoch=1, next_batch=0)
    mpath = os.path.join(path, "manifest.json")
    with open(mpath) as f:
        m = json.load(f)
    drop = next(k for k, v in m["shards"].items()
                if v["name"] == "st0_fc_weight")
    del m["shards"][drop]
    with open(mpath, "w") as f:
        json.dump(m, f)
    # digest of the manifest itself is not recorded (it IS the record),
    # so only the coverage check can catch this
    with pytest.raises(ckpt.CheckpointCorrupt, match="cover"):
        ckpt.verify_dir(path)
    assert ckpt.load_latest(cfg.dir) is None


# --------------------------------------------------------------------------
# resume consensus plumbing (single-process semantics; the dist path runs
# the same code with rank>0 reconstructing the broadcast cursor)
# --------------------------------------------------------------------------

def test_broadcast_ints_local_identity():
    kv = mx.kv.create("local")
    assert kv.broadcast_ints([3, 14, 15]) == [3, 14, 15]


def test_decide_resume_matches_load_latest_locally(tmp_path):
    mod, _ = _build_on(None)
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    mgr = ckpt.CheckpointManager(cfg, module=mod)
    assert mgr.decide_resume() is None
    mgr.save(next_epoch=1, next_batch=0)
    a = mgr.decide_resume()
    b = mgr.load_latest()
    assert a is not None and a.path == b.path


# --------------------------------------------------------------------------
# async writer: the training pause is the snapshot, not the write
# --------------------------------------------------------------------------

def _fit_small(tmp_path, num_epoch, checkpoint):
    rng = np.random.RandomState(0)
    X = rng.randn(32, 10).astype(np.float32)
    Y = rng.randint(0, 4, (32,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8)
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=8, name="fc1"),
        act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=num_epoch,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            checkpoint=checkpoint)
    return mod


def test_async_write_bounds_stall_to_snapshot(tmp_path, monkeypatch):
    """MXNET_CKPT_ASYNC=1: every save pauses training only for the
    checkpoint.snapshot span; commits happen on the writer thread under
    checkpoint.write_async, never under the foreground checkpoint.write
    span — and the commits still all land (fit drains on exit)."""
    monkeypatch.setenv("MXNET_CKPT_ASYNC", "1")
    d = str(tmp_path / "ckpts")
    saves0 = tm.counter("checkpoint.save").value
    snap0 = tm.histogram("checkpoint.snapshot").count
    async0 = tm.histogram("checkpoint.write_async").count
    sync0 = tm.histogram("checkpoint.write").count
    _fit_small(tmp_path, num_epoch=3,
               checkpoint=mx.CheckpointConfig(d, period=1))
    saves = tm.counter("checkpoint.save").value - saves0
    assert saves == 3
    assert tm.histogram("checkpoint.snapshot").count - snap0 == saves
    assert tm.histogram("checkpoint.write_async").count - async0 == saves
    assert tm.histogram("checkpoint.write").count == sync0, \
        "async mode must not write on the training thread"
    loaded = ckpt.load_latest(d)
    assert loaded is not None and loaded.next_epoch == 3
    ckpt.verify_dir(loaded.path)


def test_async_resume_sees_inflight_commit(tmp_path, monkeypatch):
    """load_latest on a manager with an in-flight async write drains
    first — rollback/resume must never read a half-landed directory."""
    monkeypatch.setenv("MXNET_CKPT_ASYNC", "1")
    mod, _ = _build_on(None)
    mod.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    mgr = ckpt.CheckpointManager(cfg, module=mod)
    try:
        mgr.save(next_epoch=1, next_batch=0)
        loaded = mgr.load_latest()
        assert loaded is not None and loaded.next_epoch == 1
    finally:
        mgr.finalize()


# --------------------------------------------------------------------------
# kill-during-save chaos matrix (subprocess; every injected phase)
# --------------------------------------------------------------------------

def _run_worker(env, timeout=240):
    e = dict(os.environ)
    clean = [p for p in e.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    e["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    e["JAX_PLATFORMS"] = "cpu"
    e.pop("XLA_FLAGS", None)
    e.update(env)
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tests",
                                      "ckpt_resume_worker.py")],
        capture_output=True, text=True, env=e, timeout=timeout, cwd=_ROOT,
    )


@pytest.mark.parametrize("phase", ["mid-shard-write", "pre-manifest",
                                   "post-manifest-pre-rename",
                                   "mid-LATEST"])
def test_sigkill_at_every_save_phase_never_loses_newest_commit(
        tmp_path, phase):
    """The chaos acceptance: life 1 dies mid-training (commits exist),
    life 2 is killed INSIDE its first save at `phase`, and whatever torn
    state that leaves, the newest previously-valid commit still loads —
    then life 3 resumes from it and finishes with the exact total update
    count of an uninterrupted run."""
    d = str(tmp_path / "ckpts")
    base = {
        "MXNET_CHECKPOINT_DIR": d,
        "MXNET_CHECKPOINT_BATCH_PERIOD": "3",
        "MXNET_CHECKPOINT_KEEP": "4",
    }
    # life 1: dies at batch 20 having committed through (epoch 2, batch 3)
    r1 = _run_worker({**base, "MXNET_FI_CRASH_AT_BATCH": "20"})
    assert r1.returncode == 17, (r1.stdout + r1.stderr)[-3000:]
    pre = ckpt.load_latest(d)
    assert pre is not None
    pre_cursor = (pre.next_epoch, pre.next_batch)
    assert pre_cursor == (2, 3)

    # life 2: resumes, then dies INSIDE its first save at `phase`
    r2 = _run_worker({**base, "MXNET_FI_CKPT_KILL_PHASE": phase})
    out2 = r2.stdout + r2.stderr
    assert r2.returncode == 17, out2[-3000:]
    assert f"faultinject: CKPT-KILL at phase {phase}" in out2, out2[-3000:]

    # invariant: whatever `phase` tore, the newest VALID commit is intact
    # and no older than what life 2 started from
    post = ckpt.load_latest(d)
    assert post is not None, f"phase {phase} lost every checkpoint"
    ckpt.verify_dir(post.path)
    post_cursor = (post.next_epoch, post.next_batch)
    assert post_cursor >= pre_cursor, \
        f"phase {phase}: {post_cursor} regressed below {pre_cursor}"

    # life 3 (no injection): resumes and completes with the
    # uninterrupted run's exact total update count
    r3 = _run_worker(dict(base))
    out3 = r3.stdout + r3.stderr
    assert r3.returncode == 0, out3[-3000:]
    assert f"RESUME epoch={post.next_epoch} batch={post.next_batch}" \
        in out3, out3[-3000:]
    done = [l for l in out3.splitlines() if l.startswith("TRAIN-DONE")]
    assert done, out3[-3000:]
    assert int(done[0].split("final_update=")[1]) == 48
    acc = float(done[0].split("acc=")[1].split()[0])
    assert acc > 0.8, f"post-chaos training stuck at {acc}"


# --------------------------------------------------------------------------
# tools/ckpt.py CLI
# --------------------------------------------------------------------------

def test_ckpt_cli_inspect_verify_reshard(tmp_path):
    """The offline CLI: inspect summarizes, verify digests (exit 1 on
    corruption), reshard consolidates a composed-mesh checkpoint into a
    single-shard commit that the elastic loader accepts."""
    src, gm = _build_on("dp2,pp4")
    src.init_optimizer(optimizer="sgd", optimizer_params=_OPT)
    cfg = mx.CheckpointConfig(str(tmp_path / "ckpts"))
    path = _save_from(src, gm, cfg)

    cli = [sys.executable, os.path.join(_ROOT, "tools", "ckpt.py")]
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")

    r = subprocess.run(cli + ["inspect", cfg.dir], capture_output=True,
                       text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "format:    v2" in r.stdout and "dp2,pp4" in r.stdout
    assert "st0_fc_weight" in r.stdout

    r = subprocess.run(cli + ["verify", cfg.dir], capture_output=True,
                       text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr[-2000:]
    assert r.stdout.startswith("OK")

    out = str(tmp_path / "resharded")
    r = subprocess.run(cli + ["reshard", cfg.dir, "--out", out,
                              "--mesh", "dp8"],
                       capture_output=True, text=True, env=env,
                       timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr[-2000:]
    loaded = ckpt.load_latest(out)
    assert loaded is not None and loaded.manifest["mesh"]["spec"] == "dp8"
    want, _ = _params_numpy(src)
    for n, arr in want.items():
        np.testing.assert_allclose(loaded.arg_params[n].asnumpy(), arr,
                                   rtol=1e-6)

    # corruption is an exit-1 CORRUPT verdict, not a silent OK
    shard = os.path.join(path, "shard-00000.params")
    with open(shard, "r+b") as f:
        f.seek(0)
        f.write(b"\xde\xad")
    r = subprocess.run(cli + ["verify", path], capture_output=True,
                       text=True, env=env, timeout=240)
    assert r.returncode == 1 and "CORRUPT" in r.stdout
