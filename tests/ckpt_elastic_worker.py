"""Worker for the composed-mesh kill-resume test
(tests/test_composed_parallelism.py).

Same deterministic MLP and convergence pin as ckpt_resume_worker.py, but
trained as a 2-stage pipeline under a composed GraftMesh (WORKER_MESH,
default dp2,pp2) with env-driven v2 sharded checkpointing. The test's
first launch sets MXNET_FI_CRASH_AT_BATCH so faultinject hard-kills the
process mid-epoch; the second sets MXNET_NUM_RESTARTS=1 so the injection
is disarmed and fit must auto-resume from the last committed elastic
checkpoint.

Prints the same machine-checkable lines as the single-host worker:
  RESUME epoch=<E> batch=<B> num_update=<N>
  TRAIN-DONE acc=<float> final_update=<N>
"""

import logging
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np


def main():
    logging.basicConfig(level=logging.INFO, stream=sys.stdout)
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.mesh import GraftMesh

    rng = np.random.RandomState(42)
    X = rng.randn(64, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)

    data = mx.sym.Variable("data")
    s0 = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="relu")
    data = mx.sym.Variable("data")
    s1 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(data, num_hidden=4, name="fc2"),
        name="softmax")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(s0, data_names=("data",), label_names=None))
    seq.add(mx.mod.Module(s1, data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    gm = GraftMesh.from_spec(os.environ.get("WORKER_MESH", "dp2,pp2"))
    with parallel.with_mesh(gm):
        seq.bind(data_shapes=[("data", (8, 10))],
                 label_shapes=[("softmax_label", (8,))])

    it = mx.io.NDArrayIter(X, Y, batch_size=8)  # 8 batches/epoch

    ckpt_dir = os.environ["MXNET_CHECKPOINT_DIR"]
    loaded = mx.checkpoint.load_latest(ckpt_dir)
    if loaded is None:
        print("RESUME epoch=-1 batch=-1 num_update=0", flush=True)
    else:
        meta = loaded.manifest.get("optimizer") or {}
        print(f"RESUME epoch={loaded.next_epoch} batch={loaded.next_batch} "
              f"num_update={meta.get('num_update', 0)}", flush=True)

    mx.random.seed(7)
    with parallel.with_mesh(gm):
        seq.fit(
            it, num_epoch=int(os.environ.get("WORKER_NUM_EPOCH", "6")),
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
        )
        metric = mx.metric.Accuracy()
        acc = seq.score(it, metric)[0][1]
    final_update = max(m._optimizer.num_update for m in seq._children())
    print(f"TRAIN-DONE acc={acc:.3f} final_update={final_update}",
          flush=True)


if __name__ == "__main__":
    main()
