"""Worker for the elastic-training chaos suite (tests/test_elastic_train.py).

Each rank trains the same tiny MLP on its own data shard through the
elastic TCP kvstore (``MXNET_KV_TRANSPORT=tcp``), driving ``Module.fit``
end-to-end: gradient rounds over the live membership, per-batch
membership-event polling, fenced resharding on kill/join, and
coordinator-restart re-seeding. Prints one machine-checkable line per
rank plus the telemetry counters the tests assert on.

Knobs (env, all optional):
  ELASTIC_EPOCHS        epochs to train (default 30)
  ELASTIC_BATCH_SLEEP   seconds to sleep per batch (stretches wall time so
                        the test can kill/add workers mid-run)
  ELASTIC_MIN_ACC       accuracy floor to assert (default 0.8; the oracle
                        tolerance — a clean dp-static run reaches ~0.95)
  ELASTIC_SKIP_ASSERT   "1": print the accuracy but do not assert (used by
                        late joiners that only see the tail of the run)
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry as tm

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert type(kv).__name__ == "ElasticDistKVStore", type(kv)

    rng = np.random.RandomState(42)
    X = rng.randn(128, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)
    Xs, Ys = X[rank::nw], Y[rank::nw]

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
        act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(Xs, Ys, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(7)  # same init on every rank
    mod.init_params(initializer=mx.init.Xavier())

    sleep_s = float(os.environ.get("ELASTIC_BATCH_SLEEP", "0") or 0)
    cb = None
    if sleep_s > 0:
        def cb(_param):
            time.sleep(sleep_s)

    epochs = int(os.environ.get("ELASTIC_EPOCHS", "30"))
    metric = mx.metric.Accuracy()
    mod.fit(
        it, num_epoch=epochs, eval_metric=metric, kvstore=kv,
        optimizer="sgd",
        optimizer_params={"learning_rate": 0.2, "rescale_grad": 1.0 / nw},
        batch_end_callback=cb,
        initializer=None,
    )
    acc = metric.get()[1]

    snap = tm.snapshot().get("kvstore", {})

    def val(k):  # gauges render as {'value': ..., 'max': ...}
        v = snap.get(k, 0)
        return v.get("value", 0) if isinstance(v, dict) else v

    stats = " ".join(
        f"{k}={val(k)}"
        for k in ("membership_epoch", "membership_size", "membership_join",
                  "peer_dead", "peer_leave", "reshard", "elastic_reseed",
                  "drop_slowest", "compress_push", "corrupt_frame_rejected",
                  "elastic_reconnect"))
    print(f"rank {rank} ELASTIC-STATS {stats}", flush=True)
    if os.environ.get("ELASTIC_SKIP_ASSERT") != "1":
        floor = float(os.environ.get("ELASTIC_MIN_ACC", "0.8"))
        assert acc > floor, \
            f"rank {rank}: elastic training stuck at {acc} (floor {floor})"
    print(f"rank {rank} ELASTIC-TRAIN OK acc={acc:.3f}", flush=True)


if __name__ == "__main__":
    main()
