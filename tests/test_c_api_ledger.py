"""C-ABI ledger (ROADMAP 5b, first slice).

The reference ships ~137 public ``MX*`` C functions (``c_api.h`` +
``c_predict_api.h``); this tree implements a subset and deliberately
excludes the rest. Before this ledger, ~20 names sat in NEITHER bucket —
invisible to review. The contract enforced here:

- ``tests/data/c_api_reference.txt`` is the survey's canonical name list;
- every reference name is in EXACTLY ONE of
  ``tests/data/c_api_implemented.txt`` / ``c_api_out_of_scope.txt``;
- the implemented bucket tells the truth: each name is genuinely declared
  in ``mxnet_tpu/native/{c_api,c_predict_api}.h``;
- the out-of-scope bucket tells the truth the other way: none of its
  names is declared.

Moving a name between buckets is a one-line data edit this test then
re-verifies — the ledger can never silently drift from the headers.
"""

import os
import re

_HERE = os.path.dirname(os.path.abspath(__file__))
_DATA = os.path.join(_HERE, "data")
_NATIVE = os.path.join(_HERE, os.pardir, "mxnet_tpu", "native")


def _read_names(fname):
    names = []
    with open(os.path.join(_DATA, fname)) as f:
        for line in f:
            name = line.split("#", 1)[0].strip()
            if name:
                names.append(name)
    return names


def _declared_names():
    """MX* names actually DECLARED (not merely mentioned in comments) in
    the native headers."""
    code_lines = []
    for header in ("c_api.h", "c_predict_api.h"):
        with open(os.path.join(_NATIVE, header)) as f:
            for line in f:
                if line.lstrip().startswith(("*", "//", "/*")):
                    continue  # rationale/comment blocks name MX* too
                code_lines.append(line)
    return set(re.findall(r"\b(MX[A-Za-z0-9]+)\s*\(", "\n".join(code_lines)))


def test_every_reference_name_in_exactly_one_bucket():
    ref = _read_names("c_api_reference.txt")
    impl = _read_names("c_api_implemented.txt")
    oos = _read_names("c_api_out_of_scope.txt")

    for label, names in (("reference", ref), ("implemented", impl),
                         ("out_of_scope", oos)):
        dupes = sorted({n for n in names if names.count(n) > 1})
        assert not dupes, f"duplicate names in {label} list: {dupes}"

    impl_s, oos_s, ref_s = set(impl), set(oos), set(ref)
    both = impl_s & oos_s
    assert not both, f"names claimed in BOTH buckets: {sorted(both)}"
    unledgered = ref_s - impl_s - oos_s
    assert not unledgered, (
        f"{len(unledgered)} reference names in NEITHER bucket (the exact "
        f"failure mode this ledger exists to end): {sorted(unledgered)}")
    phantom = (impl_s | oos_s) - ref_s
    assert not phantom, (
        f"bucket names not in the reference list: {sorted(phantom)}")
    # a truncated reference file must fail loudly, not pass vacuously
    assert len(ref_s) >= 120, f"reference list suspiciously short: {len(ref_s)}"


def test_implemented_bucket_matches_declared_headers():
    declared = _declared_names()
    impl = set(_read_names("c_api_implemented.txt"))
    missing = impl - declared
    assert not missing, (
        "ledgered as implemented but NOT declared in the native headers: "
        f"{sorted(missing)}")


def test_out_of_scope_bucket_is_honest():
    declared = _declared_names()
    oos = set(_read_names("c_api_out_of_scope.txt"))
    lying = oos & declared
    assert not lying, (
        "ledgered out-of-scope but actually declared in the native "
        f"headers — move to the implemented bucket: {sorted(lying)}")


def test_string_key_kvstore_trio_is_implemented():
    """ROADMAP 5b slice: the string-key KVStore surface moved from the
    out-of-scope bucket into the implemented one — the Ex names must be
    ledgered implemented, declared with ``const char**`` keys, and backed
    by a real dispatch in c_api.cpp (not just a declaration)."""
    trio = {"MXKVStoreInitEx", "MXKVStorePushEx", "MXKVStorePullEx"}
    impl = set(_read_names("c_api_implemented.txt"))
    oos = set(_read_names("c_api_out_of_scope.txt"))
    assert trio <= impl, f"trio not ledgered implemented: {sorted(trio - impl)}"
    assert not (trio & oos), "trio still ledgered out-of-scope"

    with open(os.path.join(_NATIVE, "c_api.h")) as f:
        header = f.read()
    for name in sorted(trio):
        m = re.search(rf"\b{name}\s*\(([^;]*)\)\s*;", header)
        assert m, f"{name} not declared in c_api.h"
        assert "const char**" in re.sub(r"\s+", " ", m.group(1)), (
            f"{name} must take `const char** keys`, got: {m.group(1)}")

    with open(os.path.join(_NATIVE, "c_api.cpp")) as f:
        impl_src = f.read()
    for name in sorted(trio):
        assert re.search(rf"\bint {name}\s*\(", impl_src), (
            f"{name} declared but not defined in c_api.cpp")


def test_header_extensions_are_known():
    """Names we declare beyond the reference surface are deliberate,
    enumerated extensions — a new one must be added here consciously (or
    to the reference list if it IS a reference name)."""
    declared = _declared_names()
    ref = set(_read_names("c_api_reference.txt"))
    known_extensions = {
        # monitor callback with the pre-aggregated stat (the reference's
        # later-era EX form, kept for the python Monitor's install path)
        "MXExecutorSetMonitorCallbackEX",
        # typedef, not a function: the updater callback's type name
        "MXKVStoreUpdater",
    }
    surprise = declared - ref - known_extensions
    assert not surprise, (
        f"undeclared header extensions: {sorted(surprise)} — ledger them")


def test_symbol_info_and_recordio_cursor_slice_is_implemented():
    """ROADMAP 5b slice: op introspection (MXSymbolGetAtomicSymbolInfo)
    and the RecordIO byte cursor (WriterTell/ReaderSeek) moved from the
    out-of-scope bucket into the implemented one — ledgered, declared,
    and backed by real definitions in c_api.cpp."""
    slice_ = {"MXSymbolGetAtomicSymbolInfo", "MXRecordIOWriterTell",
              "MXRecordIOReaderSeek"}
    impl = set(_read_names("c_api_implemented.txt"))
    oos = set(_read_names("c_api_out_of_scope.txt"))
    assert slice_ <= impl, (
        f"slice not ledgered implemented: {sorted(slice_ - impl)}")
    assert not (slice_ & oos), "slice still ledgered out-of-scope"

    with open(os.path.join(_NATIVE, "c_api.h")) as f:
        header = f.read()
    m = re.search(r"\bMXSymbolGetAtomicSymbolInfo\s*\(([^;]*)\)\s*;", header)
    assert m, "MXSymbolGetAtomicSymbolInfo not declared in c_api.h"
    sig = re.sub(r"\s+", " ", m.group(1))
    # the reference's 9-pointer signature: three string-array outs plus
    # key_var_num_args/return_type — wrapper generators depend on it
    assert sig.count("const char***") == 3, sig
    assert "key_var_num_args" in sig and "return_type" in sig, sig
    for name, arg in (("MXRecordIOWriterTell", "size_t* pos"),
                      ("MXRecordIOReaderSeek", "size_t pos")):
        m = re.search(rf"\b{name}\s*\(([^;]*)\)\s*;", header)
        assert m, f"{name} not declared in c_api.h"
        assert arg in re.sub(r"\s+", " ", m.group(1)), m.group(1)

    with open(os.path.join(_NATIVE, "c_api.cpp")) as f:
        impl_src = f.read()
    for name in sorted(slice_):
        assert re.search(rf"\bint {name}\s*\(", impl_src), (
            f"{name} declared but not defined in c_api.cpp")
