"""Detection pipeline: ImageDetRecordIter, box augmenter, Proposal, SSD e2e.

Modeled on the reference's detection stack
(``src/io/iter_image_det_recordio.cc``, ``image_det_aug_default.cc``,
``src/operator/contrib/proposal.cc``, ``example/ssd``).
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.image_det import (
    DetAugmenter, ImageDetRecordIter, pack_det_label, _parse_det_label, _iou,
)
from mxnet_tpu.recordio import MXRecordIO, pack_img
from mxnet_tpu.test_utils import assert_almost_equal

cv2 = pytest.importorskip("cv2")


def _make_rec(path, n=8, img_size=96, seed=0):
    rng = np.random.RandomState(seed)
    metas = []
    rec = MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (img_size, img_size, 3)).astype(np.uint8)
        nbox = rng.randint(1, 3)
        boxes = []
        for _ in range(nbox):
            x1, y1 = rng.uniform(0, 0.5, 2)
            w, h = rng.uniform(0.2, 0.4, 2)
            boxes.append([rng.randint(0, 3), x1, y1, min(x1 + w, 1), min(y1 + h, 1)])
        boxes = np.asarray(boxes, np.float32)
        rec.write(pack_img((4, pack_det_label(boxes), i, 0), img))
        metas.append(boxes)
    rec.close()
    return metas


def test_det_label_roundtrip():
    boxes = np.array([[1, 0.1, 0.2, 0.5, 0.6], [2, 0.3, 0.3, 0.9, 0.8]], np.float32)
    flat = pack_det_label(boxes)
    assert flat[0] == 2 and flat[1] == 5
    back = _parse_det_label(flat)
    assert_almost_equal(back, boxes)


def test_det_record_iter_shapes_and_values(tmp_path):
    path = str(tmp_path / "det.rec")
    metas = _make_rec(path, n=6)
    it = ImageDetRecordIter(
        path_imgrec=path, data_shape=(3, 64, 64), batch_size=2,
    )
    assert it.provide_label[0].shape == (2, it.max_objs, 5)
    batches = list(it)
    assert len(batches) == 3
    b0 = batches[0]
    assert b0.data[0].shape == (2, 3, 64, 64)
    lbl = b0.label[0].asnumpy()
    # no augmentation: first record's boxes survive unchanged
    n0 = len(metas[0])
    assert_almost_equal(lbl[0, :n0], metas[0], rtol=1e-5, atol=1e-5)
    assert (lbl[0, n0:] == -1).all()
    # determinism on reset without shuffle
    it.reset()
    again = next(it)
    assert_almost_equal(again.data[0].asnumpy(), b0.data[0].asnumpy())


def test_det_augmenter_mirror_flips_boxes():
    rng = np.random.RandomState(0)
    aug = DetAugmenter((3, 32, 32), rand_mirror_prob=1.0,
                       rng=np.random.RandomState(1))
    img = rng.randint(0, 255, (32, 32, 3)).astype(np.uint8)
    boxes = np.array([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    out_img, out_boxes = aug(img, boxes)
    assert_almost_equal(out_boxes[0, 1:], [0.6, 0.2, 0.9, 0.6], rtol=1e-5,
                        atol=1e-6)
    assert_almost_equal(out_img, img[:, ::-1])


def test_det_augmenter_crop_renormalises_boxes():
    rng = np.random.RandomState(2)
    img = rng.randint(0, 255, (64, 64, 3)).astype(np.uint8)
    # one box covering the center — any sampled crop overlapping it keeps it
    boxes = np.array([[1, 0.4, 0.4, 0.6, 0.6]], np.float32)
    aug = DetAugmenter((3, 32, 32), rand_crop_prob=1.0,
                       min_crop_scales=(0.7,), min_crop_overlaps=(0.1,),
                       rng=np.random.RandomState(3))
    _, out = aug(img, boxes)
    if len(out):  # center-emission may drop it for extreme crops
        assert (out[:, 1:] >= 0).all() and (out[:, 1:] <= 1).all()
        assert out[0, 1] < out[0, 3] and out[0, 2] < out[0, 4]


def test_det_augmenter_pad_shrinks_boxes():
    rng = np.random.RandomState(4)
    img = rng.randint(0, 255, (40, 40, 3)).astype(np.uint8)
    boxes = np.array([[0, 0.0, 0.0, 1.0, 1.0]], np.float32)
    aug = DetAugmenter((3, 32, 32), rand_pad_prob=1.0, max_pad_scale=2.0,
                       rng=np.random.RandomState(5))
    _, out = aug(img, boxes)
    w = out[0, 3] - out[0, 1]
    h = out[0, 4] - out[0, 2]
    assert w <= 1.0 and h <= 1.0
    assert w >= 0.45 and h >= 0.45  # max 2x pad → at least half size


def _np_proposal_oracle(cls_prob, bbox_pred, im_info, stride, scales, ratios,
                        pre_nms, post_nms, thresh, min_size):
    """Straight-line numpy reimplementation of the RPN proposal math."""
    from mxnet_tpu.ops.defs_contrib import _generate_anchors

    A = cls_prob.shape[1] // 2
    H, W = cls_prob.shape[2:]
    anchors = _generate_anchors(stride, ratios, scales)
    shift_x = np.arange(W) * stride
    shift_y = np.arange(H) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)
    shifts = np.stack([sx, sy, sx, sy], -1).reshape(-1, 1, 4)
    all_anchors = (anchors[None] + shifts).reshape(-1, 4)
    scores = cls_prob[0, A:].transpose(1, 2, 0).reshape(-1)
    deltas = bbox_pred[0].transpose(1, 2, 0).reshape(-1, 4)
    ws = all_anchors[:, 2] - all_anchors[:, 0] + 1
    hs = all_anchors[:, 3] - all_anchors[:, 1] + 1
    cx = all_anchors[:, 0] + 0.5 * (ws - 1)
    cy = all_anchors[:, 1] + 0.5 * (hs - 1)
    pcx = deltas[:, 0] * ws + cx
    pcy = deltas[:, 1] * hs + cy
    pw = np.exp(deltas[:, 2]) * ws
    ph = np.exp(deltas[:, 3]) * hs
    x1 = np.clip(pcx - 0.5 * (pw - 1), 0, im_info[0, 1] - 1)
    y1 = np.clip(pcy - 0.5 * (ph - 1), 0, im_info[0, 0] - 1)
    x2 = np.clip(pcx + 0.5 * (pw - 1), 0, im_info[0, 1] - 1)
    y2 = np.clip(pcy + 0.5 * (ph - 1), 0, im_info[0, 0] - 1)
    boxes = np.stack([x1, y1, x2, y2], 1)
    ms = min_size * im_info[0, 2]
    ok = ((x2 - x1 + 1) >= ms) & ((y2 - y1 + 1) >= ms)
    scores = np.where(ok, scores, -np.inf)
    order = np.argsort(-scores)[:pre_nms]
    boxes, scores = boxes[order], scores[order]
    keep = []
    for i in range(len(boxes)):
        if scores[i] == -np.inf:
            continue
        ok_i = True
        for j in keep:
            b1, b2 = boxes[i], boxes[j]
            xx1, yy1 = max(b1[0], b2[0]), max(b1[1], b2[1])
            xx2, yy2 = min(b1[2], b2[2]), min(b1[3], b2[3])
            inter = max(0, xx2 - xx1 + 1) * max(0, yy2 - yy1 + 1)
            a1 = (b1[2] - b1[0] + 1) * (b1[3] - b1[1] + 1)
            a2 = (b2[2] - b2[0] + 1) * (b2[3] - b2[1] + 1)
            if inter / (a1 + a2 - inter) >= thresh:
                ok_i = False
                break
        if ok_i:
            keep.append(i)
        if len(keep) >= post_nms:
            break
    return boxes[keep], scores[keep]


def test_proposal_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    A = 3 * 2  # 2 scales x 3 ratios
    H = W = 4
    scales, ratios = (8.0, 16.0), (0.5, 1.0, 2.0)
    cls_prob = rng.rand(1, 2 * A, H, W).astype(np.float32)
    bbox_pred = (rng.randn(1, 4 * A, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[64, 64, 1.0]], np.float32)
    post_nms = 8
    out, score = mx.nd.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=post_nms, threshold=0.7,
        rpn_min_size=4, scales=scales, ratios=ratios, feature_stride=16,
        output_score=True,
    )
    assert out.shape == (post_nms, 5)
    exp_boxes, exp_scores = _np_proposal_oracle(
        cls_prob, bbox_pred, im_info, 16, scales, ratios, 50, post_nms, 0.7, 4
    )
    got = out.asnumpy()
    n = len(exp_boxes)
    assert_almost_equal(got[:n, 1:], exp_boxes, rtol=1e-4, atol=1e-4)
    assert_almost_equal(score.asnumpy()[:n, 0], exp_scores, rtol=1e-4, atol=1e-5)
    assert (got[:, 0] == 0).all()  # batch index column


def test_ssd_train_step_loss_decreases(tmp_path):
    """One SSD-VGG16 config trains on synthetic detection data and the
    localisation loss decreases (VERDICT item: SSD end-to-end)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
    from train_ssd import make_synthetic_rec

    rec = str(tmp_path / "synth.rec")
    make_synthetic_rec(rec, n=4, img_size=320)
    # SSD-300 geometry: the backbone's 6 feature scales need ~300px input
    it = ImageDetRecordIter(
        path_imgrec=rec, data_shape=(3, 300, 300), batch_size=2,
        mean_r=123.0, mean_g=117.0, mean_b=104.0,
    )
    net = models.ssd.get_symbol_train(num_classes=3)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(42)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.005, "momentum": 0.9})
    losses = []
    for epoch in range(3):
        it.reset()
        tot = 0.0
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            outs = mod.get_outputs()
            tot += float(outs[1].asnumpy().sum())
        losses.append(tot)
    assert losses[-1] < losses[0], f"loc loss did not decrease: {losses}"
