"""Parallel decode plane (io_plane.DecodePool) — the ISSUE 14 contract.

Pins, in order: (1) ``input_split`` is the one sharding helper and its
shards are an exact disjoint cover; (2) the pooled ImageRecordIter /
ImageDetRecordIter batch stream is BYTE-identical to the serial path
over full epochs at a fixed seed, shuffle on and off, on both decode
planes; (3) chaos — a worker killed or hung mid-epoch is detected,
restarted and its shard reassigned with no lost or duplicated records,
visible on ``io.plane.*``; (4) backpressure bounds the reorder buffer;
(5) a pool-fed ``Module.fit`` keeps the zero-per-batch-host-sync
invariant of the async pipeline.
"""

import os
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import faultinject  # noqa: E402
from mxnet_tpu import image_det  # noqa: E402
from mxnet_tpu import recordio  # noqa: E402
from mxnet_tpu import telemetry as tm  # noqa: E402
from mxnet_tpu.base import MXNetError  # noqa: E402
from mxnet_tpu.io_plane import DecodePool, input_split  # noqa: E402

# the decode plane is the most thread-dense subsystem in the tree: run
# the whole suite under the runtime lock-order sanitizer in tier-1
pytestmark = pytest.mark.sanitize

cv2 = pytest.importorskip("cv2")


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def rec_path(tmp_path_factory):
    """37 JPEG records (prime count: exercises the dropped partial batch),
    labels = record index."""
    path = str(tmp_path_factory.mktemp("iorec") / "train.rec")
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    for i in range(37):
        img = rng.randint(0, 255, (40, 48, 3), np.uint8)
        rec.write(recordio.pack_img((0, float(i), i, 0), img))
    rec.close()
    return path


@pytest.fixture(scope="module")
def det_rec_path(tmp_path_factory):
    """19 JPEG records with detection labels (variable box counts)."""
    path = str(tmp_path_factory.mktemp("iodet") / "det.rec")
    rng = np.random.RandomState(1)
    rec = recordio.MXRecordIO(path, "w")
    for i in range(19):
        img = rng.randint(0, 255, (40, 48, 3), np.uint8)
        nbox = 1 + i % 3
        boxes = []
        for b in range(nbox):
            x1, y1 = rng.uniform(0, 0.5, 2)
            boxes.append([float(b % 4), x1, y1,
                          x1 + rng.uniform(0.1, 0.4),
                          y1 + rng.uniform(0.1, 0.4)])
        label = image_det.pack_det_label(np.array(boxes, np.float32))
        rec.write(recordio.pack_img((len(label), label, i, 0), img))
    rec.close()
    return path


def _epochs(it, n=2):
    """Materialise n epochs as (data, label) numpy pairs, resetting
    between them (also proves the coordinator RNG state matches the
    serial path ACROSS epochs, not just within one)."""
    out = []
    for _ in range(n):
        for b in it:
            out.append((np.asarray(b.data[0].asnumpy()),
                        np.asarray(b.label[0].asnumpy())))
        it.reset()
    return out


# ---------------------------------------------------------------------------
# (1) one sharding helper, exact disjoint cover
# ---------------------------------------------------------------------------
def test_input_split_exact_disjoint_cover():
    for total in (0, 1, 7, 24):
        seq = list(range(total))
        for num_parts in (1, 2, 3, 5):
            shards = [input_split(seq, i, num_parts)
                      for i in range(num_parts)]
            flat = [x for s in shards for x in s]
            assert sorted(flat) == seq  # cover, no loss
            assert len(flat) == len(set(flat))  # disjoint, no dup
    # numpy arrays shard identically (the native-scan path)
    arr = np.arange(11)
    got = np.concatenate([input_split(arr, i, 4) for i in range(4)])
    assert sorted(got.tolist()) == list(range(11))
    with pytest.raises(MXNetError):
        input_split([1, 2], 2, 2)
    with pytest.raises(MXNetError):
        input_split([1, 2], 0, 0)


def test_record_iters_share_the_split_helper(rec_path):
    """part_index/num_parts on both iterator classes is input_split:
    the per-part record sets are an exact disjoint cover."""
    seen = []
    for part in range(3):
        it = recordio.ImageRecordIter(
            path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=1,
            part_index=part, num_parts=3, use_pool=False)
        seen.extend(np.ravel(b.label[0].asnumpy())[0] for b in it)
        it.close()
    assert sorted(seen) == [float(i) for i in range(37)]


# ---------------------------------------------------------------------------
# (2) pooled vs serial bitwise epoch parity
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("shuffle", [False, True])
@pytest.mark.parametrize("native", [False, True])
def test_pooled_epoch_is_bitwise_serial(rec_path, shuffle, native):
    if native:
        from mxnet_tpu import native as _native
        if not _native.available():
            pytest.skip("native plane unavailable")

    def build(use_pool, threads):
        return recordio.ImageRecordIter(
            path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
            rand_crop=True, rand_mirror=True, shuffle=shuffle, seed=11,
            use_native=native, use_pool=use_pool,
            preprocess_threads=threads)

    serial = build(False, 2)
    pooled = build(True, 4)
    a, b = _epochs(serial), _epochs(pooled)
    serial.close(), pooled.close()
    assert len(a) == len(b) == 8  # 2 epochs x 4 full batches of 37//8
    for (da, la), (db, lb) in zip(a, b):
        assert np.array_equal(da, db)
        assert np.array_equal(la, lb)


@pytest.mark.parametrize("shuffle", [False, True])
def test_det_pooled_epoch_is_bitwise_serial(det_rec_path, shuffle):
    def build(use_pool, threads):
        return image_det.ImageDetRecordIter(
            path_imgrec=det_rec_path, data_shape=(3, 32, 32), batch_size=4,
            rand_crop_prob=0.8, rand_mirror_prob=0.5, rand_pad_prob=0.5,
            shuffle=shuffle, seed=5, use_pool=use_pool,
            preprocess_threads=threads)

    serial = build(False, 2)
    pooled = build(True, 3)
    a, b = _epochs(serial), _epochs(pooled)
    serial.close(), pooled.close()
    assert len(a) == len(b) == 8  # 2 epochs x 4 full batches of 19//4
    for (da, la), (db, lb) in zip(a, b):
        assert np.array_equal(da, db)
        assert np.array_equal(la, lb)


def test_pool_gate_env_and_kwarg(rec_path, monkeypatch):
    """MXNET_IO_POOL gates the default; use_pool overrides either way."""
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8)
    assert it._dpool is not None  # pool is the default
    it.close()
    monkeypatch.setenv("MXNET_IO_POOL", "0")
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8)
    assert it._dpool is None
    it.close()
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
        use_pool=True)
    assert it._dpool is not None
    it.close()


def test_pooled_decode_error_surfaces_every_epoch(rec_path):
    """A deterministic data error (MXNetError from decode) must surface
    on the batch that contains it, every epoch — stored in order and
    re-raised, exactly like the serial path; the worker survives."""
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
        min_crop_size=300, max_crop_size=300,  # larger than any image
        use_pool=True, preprocess_threads=2)
    for _ in range(2):
        with pytest.raises(MXNetError, match="max_crop_size"):
            it.next()
        it.reset()
    it.close()


# ---------------------------------------------------------------------------
# (3) chaos: worker crash / hang mid-epoch
# ---------------------------------------------------------------------------
def _labels_of_epoch(it):
    out = []
    for b in it:
        out.extend(np.ravel(np.asarray(b.label[0].asnumpy())).tolist())
    return out


def test_worker_crash_restarts_and_loses_nothing(rec_path, monkeypatch):
    monkeypatch.setenv("MXNET_FI_IO_CRASH_BATCHES", "1,2")
    tm.reset()
    faultinject.reset()
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
        shuffle=False, use_pool=True, preprocess_threads=3)
    labels = _labels_of_epoch(it)
    # epoch complete: every record of the 4 full batches exactly once
    assert labels == [float(i) for i in range(32)]
    assert tm.counter("faultinject.io_crash").value == 2
    assert tm.counter("io.plane.worker_crash").value == 2
    assert tm.counter("io.plane.worker_restart").value >= 2
    # injections fire once per ordinal: the next epoch runs clean AND
    # byte-identical to an uninjected serial epoch
    it.reset()
    assert _labels_of_epoch(it) == [float(i) for i in range(32)]
    it.close()


def test_worker_hang_watchdog_reassigns(rec_path, monkeypatch):
    monkeypatch.setenv("MXNET_FI_IO_HANG_BATCHES", "0")
    monkeypatch.setenv("MXNET_FI_IO_HANG_MS", "30000")
    monkeypatch.setenv("MXNET_IO_WORKER_TIMEOUT_MS", "200")
    tm.reset()
    faultinject.reset()
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
        shuffle=False, use_pool=True, preprocess_threads=2)
    labels = _labels_of_epoch(it)
    it.close()
    assert labels == [float(i) for i in range(32)]
    assert tm.counter("faultinject.io_hang").value == 1
    assert tm.counter("io.plane.worker_stall").value == 1
    assert tm.counter("io.plane.worker_restart").value >= 1


def test_crash_chaos_stream_stays_bitwise_correct(rec_path, monkeypatch):
    """Under injected worker death the delivered bytes must STILL equal
    the serial stream — reassignment re-decodes from the same payload."""
    serial = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
        rand_crop=True, rand_mirror=True, shuffle=True, seed=3,
        use_pool=False)
    want = _epochs(serial, n=1)
    serial.close()
    monkeypatch.setenv("MXNET_FI_IO_CRASH_BATCHES", "0,3")
    faultinject.reset()
    pooled = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=8,
        rand_crop=True, rand_mirror=True, shuffle=True, seed=3,
        use_pool=True, preprocess_threads=3)
    got = _epochs(pooled, n=1)
    pooled.close()
    assert len(want) == len(got)
    for (da, la), (db, lb) in zip(want, got):
        assert np.array_equal(da, db)
        assert np.array_equal(la, lb)


# ---------------------------------------------------------------------------
# (4) backpressure
# ---------------------------------------------------------------------------
def test_backpressure_bounds_reorder_buffer(rec_path, monkeypatch):
    """A slow consumer must not let the pool buffer the whole epoch:
    the queue-depth high-water mark stays within MXNET_IO_QUEUE_DEPTH."""
    monkeypatch.setenv("MXNET_IO_QUEUE_DEPTH", "2")
    tm.reset()
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=4,
        shuffle=False, use_pool=True, preprocess_threads=2)
    import time

    n = 0
    for _ in it:
        time.sleep(0.05)  # consumer slower than decode
        n += 1
    it.close()
    assert n == 9  # 37 // 4
    assert tm.gauge("io.plane.queue_depth").max <= 2


def test_pool_raw_roundtrip_order_and_restartability():
    """DecodePool alone: out-of-order completion is reordered; a second
    start_epoch discards stale state."""
    import time

    def decode(payload, _state):
        time.sleep(0.002 * (payload % 3))
        return payload * 10

    pool = DecodePool(decode, num_workers=3, depth=4, timeout_ms=0)
    pool.start_epoch(list(range(12)))
    assert [pool.next_result() for _ in range(5)] == [0, 10, 20, 30, 40]
    pool.start_epoch(list(range(6)))  # mid-epoch reset, stale discarded
    assert [pool.next_result() for _ in range(6)] == [
        0, 10, 20, 30, 40, 50]
    with pytest.raises(MXNetError, match="exhausted"):
        pool.next_result()
    pool.close()


# ---------------------------------------------------------------------------
# (5) fit integration: zero per-batch host syncs with the pool active
# ---------------------------------------------------------------------------
_SYNC_COUNTERS = ("ndarray.asnumpy", "ndarray.wait_to_read",
                  "metric.numpy_fallback", "metric.drain_sync",
                  "executor.jit_compile")


def _tiny_cnn():
    d = mx.sym.Variable("data")
    h = mx.sym.Convolution(d, num_filter=4, kernel=(3, 3), name="c1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.Flatten(h)
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _fit_over_pool(rec_path, nbatches, num_epoch=2):
    it = recordio.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 16, 16), batch_size=4,
        shuffle=False, use_pool=True, preprocess_threads=2)
    # trim the epoch to nbatches by narrowing the record order (the
    # fixture's 37 records give at most 9 full batches)
    it._order = it._order[:nbatches * 4]
    it.reset()
    mod = mx.mod.Module(_tiny_cnn(), context=mx.cpu())
    mx.random.seed(11)
    tm.reset()
    mod.fit(it, eval_metric=mx.metric.Accuracy(), num_epoch=num_epoch,
            optimizer_params={"learning_rate": 0.05})
    it.close()
    return {name: tm.counter(name).value for name in _SYNC_COUNTERS}


def test_fit_over_pool_zero_per_batch_sync(rec_path):
    """Module.fit fed by the pooled ImageRecordIter (through the default
    DevicePrefetchIter staging) keeps the async-pipeline invariant:
    blocking sync counters at zero, metric drains O(epochs), compiles
    O(1) — and the totals must NOT scale when the batch count
    doubles (doubled batches + same counters = zero per-batch syncs
    and zero steady-state compiles)."""
    c_small = _fit_over_pool(rec_path, 4)
    small_staged = tm.counter("io.prefetch.batches").value
    small_decoded = tm.counter("io.plane.batches").value
    c_large = _fit_over_pool(rec_path, 8)
    assert c_small == c_large, (
        f"per-batch host sync scaled with the pool active: "
        f"4 batches -> {c_small}, 8 batches -> {c_large}")
    assert c_large["ndarray.asnumpy"] == 0
    assert c_large["ndarray.wait_to_read"] == 0
    assert c_large["metric.numpy_fallback"] == 0
    assert c_large["metric.drain_sync"] == 2  # one per epoch
    # the plane actually carried the run, through the prefetch stage
    assert small_decoded >= 4 * 2
    assert small_staged >= 4 * 2
    assert tm.counter("io.plane.batches").value >= 8 * 2
    # records count on the WORKER at decode time; the head of epoch 1
    # may be decoded ahead, before _fit_over_pool's tm.reset(), so only
    # bound it by epoch 2 (fully inside the fit) to stay timing-proof
    assert tm.counter("io.plane.records").value >= 8 * 4
