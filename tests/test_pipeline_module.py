"""SequentialModule -> GPipe lowering under a 'pp' mesh axis.

The oracle is serial equivalence: the pipelined module must produce the
same outputs, gradients and post-update parameters as the identical layer
stack trained as one plain Module (reference "usable from user code" bar:
example/model-parallel-lstm — placement only; the schedule is TPU-native
surface, parallel/pipeline_module.py).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import assert_almost_equal

BATCH, DIM, HID, NCLS = 16, 8, 12, 5


def _stage_syms():
    """Four heterogeneous stages; the last carries the loss head."""
    syms = []
    for i in range(3):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=HID, name=f"st{i}_fc")
        syms.append(mx.sym.Activation(fc, act_type="tanh", name=f"st{i}_act"))
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=NCLS, name="st3_fc")
    syms.append(mx.sym.SoftmaxOutput(fc, name="softmax"))
    return syms


def _chain_sym():
    """The same four stages composed as one symbol (serial oracle)."""
    h = mx.sym.Variable("data")
    for i in range(3):
        h = mx.sym.FullyConnected(h, num_hidden=HID, name=f"st{i}_fc")
        h = mx.sym.Activation(h, act_type="tanh", name=f"st{i}_act")
    h = mx.sym.FullyConnected(h, num_hidden=NCLS, name="st3_fc")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _build_seq(mesh, microbatches=None):
    syms = _stage_syms()
    seq = mx.mod.SequentialModule(pipeline_microbatches=microbatches)
    for i, s in enumerate(syms[:-1]):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    seq.add(mx.mod.Module(syms[-1], data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    return seq


def _batch(rs):
    data = mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))
    label = mx.nd.array(rs.randint(0, NCLS, (BATCH,)).astype(np.float32))
    return mx.io.DataBatch(data=[data], label=[label])


def test_sequential_module_lowers_to_pipeline():
    mesh = parallel.make_mesh({"pp": 4})
    seq = _build_seq(mesh)
    assert seq._pp_engine is not None
    assert seq._pp_engine.S == 4 and seq._pp_engine.M == 4
    assert not seq._pp_engine.homogeneous  # loss head differs


def test_pipelined_matches_serial_loss_grads_and_update():
    rs = np.random.RandomState(7)
    mesh = parallel.make_mesh({"pp": 4})
    seq = _build_seq(mesh)

    ref = mx.mod.Module(_chain_sym(), context=mx.cpu())
    ref.bind(data_shapes=[("data", (BATCH, DIM))],
             label_shapes=[("softmax_label", (BATCH,))])
    args, auxs = seq.get_params()
    ref.init_params(arg_params={k: v.copy() for k, v in args.items()},
                    aux_params={k: v.copy() for k, v in auxs.items()},
                    initializer=None)

    batch = _batch(rs)
    seq.forward(batch, is_train=True)
    seq.backward()
    ref.forward(batch, is_train=True)
    ref.backward()

    out_pp = seq.get_outputs()[0].asnumpy()
    out_ref = ref.get_outputs()[0].asnumpy()
    assert_almost_equal(out_pp, out_ref, rtol=1e-5, atol=1e-6)

    # per-parameter gradient equivalence (pipelined grads land in the
    # child executors)
    ref_grads = {n: g.asnumpy() for n, g in
                 ref._exec_group._exec.grad_dict.items() if g is not None}
    for info in seq._pp_engine.infos:
        for (u, n) in info.param_entries:
            g = info.units[u].exec_.grad_dict[n].asnumpy()
            assert_almost_equal(g, ref_grads[n], rtol=1e-4, atol=1e-6,
                                names=(f"pp:{n}", f"serial:{n}"))

    # one optimizer step then parameter equivalence
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    ref.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    seq.update()
    ref.update()
    a_pp, _ = seq.get_params()
    a_ref, _ = ref.get_params()
    for n in a_ref:
        assert_almost_equal(a_pp[n].asnumpy(), a_ref[n].asnumpy(),
                            rtol=1e-4, atol=1e-6, names=(n, n))


def test_pipelined_fit_converges():
    rs = np.random.RandomState(3)
    mesh = parallel.make_mesh({"pp": 4})
    seq = _build_seq(mesh)
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    # learnable synthetic task: labels from a fixed random projection
    w = rs.randn(DIM, NCLS).astype(np.float32)
    data = rs.randn(256, DIM).astype(np.float32)
    label = np.argmax(data @ w, axis=1).astype(np.float32)
    metric = mx.metric.Accuracy()
    for epoch in range(12):
        metric.reset()
        for i in range(0, 256, BATCH):
            b = mx.io.DataBatch(
                data=[mx.nd.array(data[i:i + BATCH])],
                label=[mx.nd.array(label[i:i + BATCH])])
            seq.forward(b, is_train=True)
            seq.backward()
            seq.update()
            seq.update_metric(metric, b.label)
    assert metric.get()[1] > 0.8, metric.get()


def test_homogeneous_stages_stack_and_match_serial():
    rs = np.random.RandomState(1)
    mesh = parallel.make_mesh({"pp": 4})
    syms = []
    for i in range(4):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=DIM, name=f"blk{i}_fc")
        syms.append(mx.sym.Activation(fc, act_type="tanh",
                                      name=f"blk{i}_act"))
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))], for_training=False)
    seq.init_params(initializer=mx.init.Uniform(0.5))
    assert seq._pp_engine is not None and seq._pp_engine.homogeneous

    h = mx.sym.Variable("data")
    for i in range(4):
        h = mx.sym.FullyConnected(h, num_hidden=DIM, name=f"blk{i}_fc")
        h = mx.sym.Activation(h, act_type="tanh", name=f"blk{i}_act")
    ref = mx.mod.Module(h, context=mx.cpu(), label_names=None)
    ref.bind(data_shapes=[("data", (BATCH, DIM))], for_training=False)
    args, auxs = seq.get_params()
    ref.init_params(arg_params={k: v.copy() for k, v in args.items()},
                    aux_params=None, initializer=None)

    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))],
        label=None)
    seq.forward(batch, is_train=False)
    ref.forward(batch, is_train=False)
    assert_almost_equal(seq.get_outputs()[0].asnumpy(),
                        ref.get_outputs()[0].asnumpy(),
                        rtol=1e-5, atol=1e-6)


def test_homogeneous_with_batchnorm_aux_updates():
    # stacked-mode aux states (BN moving stats) must survive the P('pp')
    # plumbing and update from the schedule's final microbatch
    rs = np.random.RandomState(2)
    mesh = parallel.make_mesh({"pp": 4})
    syms = []
    for i in range(4):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=DIM, name=f"bn{i}_fc")
        b = mx.sym.BatchNorm(fc, name=f"bn{i}_bn", fix_gamma=False)
        syms.append(mx.sym.Activation(b, act_type="tanh",
                                      name=f"bn{i}_act"))
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    assert seq._pp_engine.homogeneous
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))],
        label=None)
    seq.forward(batch, is_train=True)
    _, auxs = seq.get_params()
    moved = [n for n, v in auxs.items()
             if "moving_mean" in n and np.abs(v.asnumpy()).max() > 1e-8]
    assert len(moved) == 4, f"BN moving stats did not update: {moved}"


def test_pipelined_label_less_inference():
    rs = np.random.RandomState(5)
    mesh = parallel.make_mesh({"pp": 4})
    seq = _build_seq(mesh)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))],
        label=None)
    seq.forward(batch, is_train=False)  # predict/score path: no labels
    out = seq.get_outputs()[0].asnumpy()
    assert out.shape == (BATCH, NCLS)
    assert_almost_equal(out.sum(axis=1), np.ones(BATCH), rtol=1e-4)


def test_pipelined_rejects_grad_req_add():
    mesh = parallel.make_mesh({"pp": 4})
    syms = _stage_syms()
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms[:-1]):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    seq.add(mx.mod.Module(syms[-1], data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with pytest.raises(MXNetError, match="add"):
        with parallel.with_mesh(mesh):
            seq.bind(data_shapes=[("data", (BATCH, DIM))],
                     label_shapes=[("softmax_label", (BATCH,))],
                     grad_req="add")


def test_shape_differing_stages_use_composed_mode():
    # structurally identical graphs whose bound widths differ cannot
    # stack; they must quietly take the composed path, not crash
    mesh = parallel.make_mesh({"pp": 4})
    seq = mx.mod.SequentialModule()
    for i in range(4):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=HID, name=f"w{i}_fc")
        seq.add(mx.mod.Module(
            mx.sym.Activation(fc, act_type="tanh", name=f"w{i}_act"),
            data_names=("data",), label_names=None), auto_wiring=i > 0)
    with parallel.with_mesh(mesh):
        # stage 0 weight is (HID, DIM), later stages (HID, HID)
        seq.bind(data_shapes=[("data", (BATCH, DIM))], for_training=False)
    seq.init_params(initializer=mx.init.Uniform(0.5))
    assert not seq._pp_engine.homogeneous
    batch = mx.io.DataBatch(
        data=[mx.nd.array(np.random.RandomState(0).randn(
            BATCH, DIM).astype(np.float32))], label=None)
    seq.forward(batch, is_train=False)
    assert seq.get_outputs()[0].shape == (BATCH, HID)


def test_pipeline_validation_errors():
    mesh = parallel.make_mesh({"pp": 4})
    syms = _stage_syms()
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms[:2]):  # 2 stages on a pp=4 mesh
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    with pytest.raises(MXNetError, match="pp axis of size"):
        with parallel.with_mesh(mesh):
            seq.bind(data_shapes=[("data", (BATCH, DIM))])

    seq2 = mx.mod.SequentialModule(pipeline_microbatches=5)
    for i, s in enumerate(_stage_syms()[:-1]):
        seq2.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                 auto_wiring=i > 0)
    seq2.add(mx.mod.Module(_stage_syms()[-1], data_names=("data",),
                           label_names=("softmax_label",)),
             take_labels=True, auto_wiring=True)
    with pytest.raises(MXNetError, match="not divisible"):
        with parallel.with_mesh(mesh):
            seq2.bind(data_shapes=[("data", (BATCH, DIM))],
                      label_shapes=[("softmax_label", (BATCH,))])


def test_children_group_into_fewer_stages():
    """More children than pipeline ranks: contiguous balanced grouping
    (here 6 children over pp=2 -> stages of 3+3), still serial-exact."""
    rs = np.random.RandomState(9)
    mesh = parallel.make_mesh({"pp": 2})
    seq = mx.mod.SequentialModule()
    for i in range(5):
        d = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(d, num_hidden=HID, name=f"g{i}_fc")
        seq.add(mx.mod.Module(
            mx.sym.Activation(fc, act_type="tanh", name=f"g{i}_act"),
            data_names=("data",), label_names=None), auto_wiring=i > 0)
    d = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(d, num_hidden=NCLS, name="g5_fc")
    seq.add(mx.mod.Module(mx.sym.SoftmaxOutput(fc, name="softmax"),
                          data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    eng = seq._pp_engine
    assert eng.S == 2 and [len(i.units) for i in eng.infos] == [3, 3]

    h = mx.sym.Variable("data")
    for i in range(5):
        h = mx.sym.FullyConnected(h, num_hidden=HID, name=f"g{i}_fc")
        h = mx.sym.Activation(h, act_type="tanh", name=f"g{i}_act")
    h = mx.sym.FullyConnected(h, num_hidden=NCLS, name="g5_fc")
    ref = mx.mod.Module(mx.sym.SoftmaxOutput(h, name="softmax"),
                        context=mx.cpu())
    ref.bind(data_shapes=[("data", (BATCH, DIM))],
             label_shapes=[("softmax_label", (BATCH,))])
    args, auxs = seq.get_params()
    ref.init_params(arg_params={k: v.copy() for k, v in args.items()},
                    aux_params={k: v.copy() for k, v in auxs.items()},
                    initializer=None)
    batch = _batch(rs)
    seq.forward(batch, is_train=True)
    seq.backward()
    ref.forward(batch, is_train=True)
    ref.backward()
    assert_almost_equal(seq.get_outputs()[0].asnumpy(),
                        ref.get_outputs()[0].asnumpy(),
                        rtol=1e-5, atol=1e-6)
    ref_grads = {n: g.asnumpy() for n, g in
                 ref._exec_group._exec.grad_dict.items() if g is not None}
    for info in seq._pp_engine.infos:
        for (u, n) in info.param_entries:
            g = info.units[u].exec_.grad_dict[n].asnumpy()
            assert_almost_equal(g, ref_grads[n], rtol=1e-4, atol=1e-6,
                                names=(f"pp:{n}", f"serial:{n}"))


def test_pipeline_with_dropout_trains():
    """Dropout inside pipeline stages: per-(tick, stage, unit) rng folding
    must produce stochastic but trainable behavior."""
    rs = np.random.RandomState(4)
    mesh = parallel.make_mesh({"pp": 2})
    seq = mx.mod.SequentialModule()
    d = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(d, num_hidden=HID, name="dr0_fc")
    drop = mx.sym.Dropout(mx.sym.Activation(fc, act_type="tanh"), p=0.3,
                          name="dr0_drop")
    seq.add(mx.mod.Module(drop, data_names=("data",), label_names=None))
    d = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(d, num_hidden=NCLS, name="dr1_fc")
    seq.add(mx.mod.Module(mx.sym.SoftmaxOutput(fc, name="softmax"),
                          data_names=("data",),
                          label_names=("softmax_label",)),
            take_labels=True, auto_wiring=True)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))],
                 label_shapes=[("softmax_label", (BATCH,))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = _batch(rs)
    # stochasticity isolated from updates: two train forwards, no update
    seq.forward(batch, is_train=True)
    o1 = seq.get_outputs()[0].asnumpy()
    seq.forward(batch, is_train=True)
    o2 = seq.get_outputs()[0].asnumpy()
    assert not np.allclose(o1, o2)  # dropout mask advanced between runs
    seq.backward()
    seq.update()
    # eval mode is deterministic (dropout off)
    seq.forward(batch, is_train=False)
    e1 = seq.get_outputs()[0].asnumpy()
    seq.forward(batch, is_train=False)
    e2 = seq.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(e1, e2, rtol=1e-6)


def test_grouped_stages_with_batchnorm_aux():
    """BN aux states inside multi-child stages: the per-unit aux entry
    plumbing must route updates back to the right child executors."""
    rs = np.random.RandomState(6)
    mesh = parallel.make_mesh({"pp": 2})
    seq = mx.mod.SequentialModule()
    for i in range(4):
        d = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(d, num_hidden=DIM, name=f"gb{i}_fc")
        bn = mx.sym.BatchNorm(fc, fix_gamma=False, name=f"gb{i}_bn")
        seq.add(mx.mod.Module(
            mx.sym.Activation(bn, act_type="tanh", name=f"gb{i}_act"),
            data_names=("data",), label_names=None), auto_wiring=i > 0)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))])
    seq.init_params(initializer=mx.init.Uniform(0.5))
    eng = seq._pp_engine
    assert [len(i.units) for i in eng.infos] == [2, 2]
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))],
        label=None)
    seq.forward(batch, is_train=True)
    _, auxs = seq.get_params()
    all_means = [n for n in auxs if "moving_mean" in n]
    assert len(all_means) == 4, sorted(auxs)
    moved = [n for n in all_means
             if np.abs(auxs[n].asnumpy()).max() > 1e-8]
    stuck = sorted(set(all_means) - set(moved))
    assert not stuck, f"BN stats missing updates: {stuck}"


def test_composed_params_shard_per_stage():
    """VERDICT r4 weak #2: heterogeneous (composed) pipelines must scale
    parameter memory ~1/S — each pp rank holds only its stage's packed
    row, not a replica of every stage."""
    mesh = parallel.make_mesh({"pp": 4})
    seq = _build_seq(mesh)
    rs = np.random.RandomState(3)
    seq._pp_engine.retain_packed = True
    seq._pp_engine.run(_batch(rs), is_train=True)
    packed = seq._pp_engine._packed_params
    assert packed, "composed engine should pack params"
    total = live = 0
    for buf in packed.values():
        shards = buf.addressable_shards
        assert len(shards) == 4
        per_dev = {s.device: s.data.nbytes for s in shards}
        total += buf.nbytes
        live += max(per_dev.values())
    # each device holds one (1, Lmax) row per dtype = total/S exactly
    assert live * 4 == total
    # padding slack is bounded: rows pad to the longest stage plus the
    # 128-element lane-alignment floor (which dominates at toy sizes)
    raw = 0
    for info in seq._pp_engine.infos:
        for (u, n) in info.param_entries:
            arr = info.units[u].exec_.arg_dict[n]
            raw += arr._data.nbytes
    align_floor = 4 * len(packed) * 128 * 8  # S rows x dtypes x 128 lanes
    assert total <= 2 * max(raw, 1) + align_floor


def test_composed_sharded_aux_and_grads_roundtrip():
    """Packed composed grads/aux unpack back to per-tensor values that
    match the serial oracle (covered by equivalence tests) and land in the
    child executors with the right shapes/dtypes."""
    mesh = parallel.make_mesh({"pp": 4})
    seq = _build_seq(mesh)
    rs = np.random.RandomState(5)
    seq._pp_engine.run(_batch(rs), is_train=True)
    for info in seq._pp_engine.infos:
        for (u, n) in info.param_entries:
            g = info.units[u].exec_.grad_dict.get(n)
            w = info.units[u].exec_.arg_dict[n]
            if g is not None:
                assert tuple(g.shape) == tuple(w.shape)
                assert np.isfinite(np.asarray(g.asnumpy())).all()


def test_pipelined_bn_stats_match_serial():
    """VERDICT r4 #7: BN moving stats under GPipe follow serial semantics.

    The masked per-tick aux updates average to one serial EMA update with
    full-batch statistics: moving_mean matches the serial oracle to fp
    tolerance (mean of equal microbatch means == full-batch mean);
    moving_var keeps per-microbatch granularity, i.e. underestimates the
    full-batch variance by the between-microbatch mean spread (the
    reference's non-sync multi-device BN behaves identically), so it is
    compared with a bound."""
    rs = np.random.RandomState(4)
    mesh = parallel.make_mesh({"pp": 4})
    syms = []
    for i in range(4):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=DIM, name=f"p{i}_fc")
        b = mx.sym.BatchNorm(fc, name=f"p{i}_bn", fix_gamma=False,
                             momentum=0.9)
        syms.append(mx.sym.Activation(b, act_type="tanh", name=f"p{i}_act"))
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))])
    mx.random.seed(31)
    seq.init_params(initializer=mx.init.Uniform(0.5))

    # serial oracle: same chain as one plain Module with the same params
    h = mx.sym.Variable("data")
    for i in range(4):
        h = mx.sym.FullyConnected(h, num_hidden=DIM, name=f"p{i}_fc")
        h = mx.sym.BatchNorm(h, name=f"p{i}_bn", fix_gamma=False,
                             momentum=0.9)
        h = mx.sym.Activation(h, act_type="tanh", name=f"p{i}_act")
    ser = mx.mod.Module(h, data_names=("data",), label_names=None)
    ser.bind(data_shapes=[("data", (BATCH, DIM))])
    args, auxs = seq.get_params()
    # deep-copy the step-start state: get_params returns live views, and
    # the pipelined forward below mutates the originals
    args = {k: v.copy() for k, v in args.items()}
    auxs = {k: v.copy() for k, v in auxs.items()}
    ser.set_params(args, auxs)

    xs = rs.randn(BATCH, DIM).astype(np.float32)
    batch = mx.io.DataBatch(data=[mx.nd.array(xs)], label=None)
    seq.forward(batch, is_train=True)
    _, aux_p = seq.get_params()

    # oracle: microbatch-granular serial semantics — run each microbatch
    # through the serial chain FROM THE STEP-START aux and average the
    # EMA updates (per-microbatch normalization is what GPipe, gradient
    # accumulation and the reference's multi-device non-sync BN all do)
    M = seq._pp_engine.M
    mb = BATCH // M
    sums = None
    for k in range(M):
        ser.set_params(args, auxs)  # reset aux to step start
        ser.forward(mx.io.DataBatch(
            data=[mx.nd.array(xs[k * mb:(k + 1) * mb])], label=None),
            is_train=True)
        ser.get_outputs()[0].asnumpy()  # materialize the scheduled pass
        vals = {n: a.asnumpy().copy()
                for n, a in ser._exec_group._exec.aux_dict.items()}
        sums = vals if sums is None else {
            n: sums[n] + vals[n] for n in sums}
    aux_oracle = {n: v / M for n, v in sums.items()}
    for name, s_ in aux_oracle.items():
        np.testing.assert_allclose(
            aux_p[name].asnumpy(), s_, rtol=5e-4, atol=5e-4, err_msg=name)
    # stage-0 bonus (linear input): the microbatch-mean average equals the
    # FULL-batch serial mean exactly, so the first BN's moving_mean
    # matches classic serial semantics too
    fc0 = xs @ args["p0_fc_weight"].asnumpy().T + args["p0_fc_bias"].asnumpy()
    np.testing.assert_allclose(
        aux_p["p0_bn_moving_mean"].asnumpy(), 0.1 * fc0.mean(0),
        rtol=5e-4, atol=5e-4)


def test_pipelined_eval_preserves_aux_bit_exact():
    """Inference forwards must not perturb BN moving stats (eval BN passes
    aux through; the train-path averaging must not run)."""
    mesh = parallel.make_mesh({"pp": 4})
    syms = []
    for i in range(4):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data, num_hidden=DIM, name=f"e{i}_fc")
        b = mx.sym.BatchNorm(fc, name=f"e{i}_bn", fix_gamma=False)
        syms.append(mx.sym.Activation(b, act_type="tanh", name=f"e{i}_act"))
    seq = mx.mod.SequentialModule()
    for i, s in enumerate(syms):
        seq.add(mx.mod.Module(s, data_names=("data",), label_names=None),
                auto_wiring=i > 0)
    with parallel.with_mesh(mesh):
        seq.bind(data_shapes=[("data", (BATCH, DIM))])
    mx.random.seed(8)
    seq.init_params(initializer=mx.init.Uniform(0.5))
    rs = np.random.RandomState(9)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.randn(BATCH, DIM).astype(np.float32))],
        label=None)
    # seed the stats with one training step, snapshot, then eval twice
    seq.forward(batch, is_train=True)
    _, aux0 = seq.get_params()
    aux0 = {k: v.asnumpy().copy() for k, v in aux0.items()}
    seq.forward(batch, is_train=False)
    seq.forward(batch, is_train=False)
    _, aux1 = seq.get_params()
    for k in aux0:
        np.testing.assert_array_equal(aux0[k], aux1[k].asnumpy(),
                                      err_msg=k)
