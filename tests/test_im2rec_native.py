"""Native im2rec pack path (reference tools/im2rec.cc equivalent).

The reference ships a C++ packer because packing ImageNet through python
costs hours; the TPU build packs through the native io plane
(``mxio_pack_list``). Contract pinned here: pass-through packing is
BYTE-IDENTICAL to the python packer (.rec and .idx), the re-encode path
produces records the iterators read back correctly, and the native
packer's measured throughput beats the python multiprocess packer.
"""

import importlib.util
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native, recordio

cv2 = pytest.importorskip("cv2")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _im2rec():
    spec = importlib.util.spec_from_file_location(
        "im2rec", os.path.join(_ROOT, "tools", "im2rec.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["im2rec"] = mod  # Pool workers unpickle _pack_one by name
    spec.loader.exec_module(mod)
    return mod


def _make_images(root, n, hw=(48, 64), seed=0):
    rng = np.random.RandomState(seed)
    os.makedirs(root, exist_ok=True)
    for i in range(n):
        img = rng.randint(0, 255, hw + (3,), np.uint8)
        cv2.imwrite(os.path.join(root, f"img_{i:04d}.jpg"), img)


@pytest.fixture(scope="module")
def plane_ok():
    if native.available() is False or native._load() is None:
        pytest.skip("native io plane unavailable")


def test_passthrough_pack_byte_identical(tmp_path, plane_ok):
    root = str(tmp_path / "imgs")
    _make_images(root, 24)
    im2rec = _im2rec()
    images = list(im2rec.list_image(root))
    lst = str(tmp_path / "data.lst")
    im2rec.write_list(lst, images)

    # python pass-through
    py_prefix = str(tmp_path / "py_data")
    os.link(lst, py_prefix + ".lst")

    class A:
        resize = 0
        quality = -1
        color = 1
        num_thread = 1

    im2rec.im2rec(py_prefix, root, A)

    nat_prefix = str(tmp_path / "nat_data")
    n = native.pack_list(lst, root, nat_prefix + ".rec",
                         nat_prefix + ".idx", num_threads=3,
                         resize=0, quality=-1)
    assert n == 24
    with open(py_prefix + ".rec", "rb") as a, \
            open(nat_prefix + ".rec", "rb") as b:
        assert a.read() == b.read(), ".rec bytes differ"
    with open(py_prefix + ".idx") as a, open(nat_prefix + ".idx") as b:
        assert a.read() == b.read(), ".idx bytes differ"


def test_native_reencode_pack_reads_back(tmp_path, plane_ok):
    root = str(tmp_path / "imgs")
    _make_images(root, 10, hw=(80, 120), seed=3)
    im2rec = _im2rec()
    images = [(i, f, float(i % 4)) for i, f, _l in im2rec.list_image(root)]
    lst = str(tmp_path / "data.lst")
    im2rec.write_list(lst, images)
    prefix = str(tmp_path / "enc")
    n = native.pack_list(lst, root, prefix + ".rec", prefix + ".idx",
                         num_threads=2, resize=64, quality=85)
    assert n == 10
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    for i, _f, lab in images:
        hdr, img = recordio.unpack_img(rec.read_idx(i))
        assert hdr.id == i and float(hdr.label) == lab
        assert min(img.shape[:2]) == 64  # shorter edge resized
    rec.close()
    # the image iterator consumes the native-packed file end-to-end
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, 48, 48), batch_size=5,
        shuffle=False,
    )
    batch = next(iter(it))
    assert batch.data[0].shape == (5, 3, 48, 48)


def test_native_pack_throughput_edge(tmp_path, plane_ok):
    """Measured pack-throughput edge over the python multiprocess packer
    (decode+resize+re-encode, 4 workers each)."""
    root = str(tmp_path / "imgs")
    n_img = 96
    _make_images(root, n_img, hw=(256, 256), seed=1)
    im2rec = _im2rec()
    images = list(im2rec.list_image(root))
    lst = str(tmp_path / "data.lst")
    im2rec.write_list(lst, images)

    py_prefix = str(tmp_path / "py")
    os.link(lst, py_prefix + ".lst")

    class A:
        resize = 128
        quality = 90
        color = 1
        num_thread = 4

    tic = time.time()
    im2rec.im2rec(py_prefix, root, A)
    t_py = time.time() - tic

    nat_prefix = str(tmp_path / "nat")
    tic = time.time()
    n = native.pack_list(lst, root, nat_prefix + ".rec",
                         nat_prefix + ".idx", num_threads=4,
                         resize=128, quality=90)
    t_nat = time.time() - tic
    assert n == n_img
    ratio = t_py / t_nat
    print(f"\nnative pack edge: python {n_img / t_py:.0f} img/s vs native "
          f"{n_img / t_nat:.0f} img/s -> {ratio:.1f}x")
    # short-burst regime (one shard): the python packer pays Pool worker
    # spawn + per-record IPC; the native plane threads in-process. At bulk
    # scale the two converge (~230 img/s each at 8 workers on this host,
    # 480x360->256 q90: cv2 is C++ SIMD underneath too) — measured numbers
    # in docs/architecture.md. Conservative CI floor:
    assert ratio > 1.05
