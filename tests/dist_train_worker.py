"""Worker for the multi-process distributed TRAINING test.

The dist_lenet analogue (reference ``tests/nightly/dist_lenet.py``): every
rank trains the same model on its own data shard, gradients reduce across
processes through the dist_sync kvstore, and all ranks must converge to
IDENTICAL parameters.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    # deterministic dataset, sharded by rank (reference part_index pattern)
    rng = np.random.RandomState(42)
    X = rng.randn(128, 10).astype(np.float32)
    W = rng.randn(10, 4).astype(np.float32)
    Y = X.dot(W).argmax(1).astype(np.float32)
    Xs, Ys = X[rank::nw], Y[rank::nw]

    data = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=16, name="fc1"),
                          act_type="relu")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=4, name="fc2"),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(Xs, Ys, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(7)  # same init on every rank
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(
        kvstore=kv, optimizer="sgd",
        optimizer_params={"learning_rate": 0.2,
                          "rescale_grad": 1.0 / nw},
    )
    metric = mx.metric.Accuracy()
    for epoch in range(25):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
    acc = metric.get()[1]
    assert acc > 0.8, f"rank {rank}: dist training stuck at {acc}"

    # parameters must be identical across ranks after sync training
    # (raw allreduce — kv.push would route through the installed optimizer)
    params = mod.get_params()[0]
    digest = float(sum(v.asnumpy().astype(np.float64).sum() for v in params.values()))
    summed = np.asarray(kv._allreduce(mx.nd.array([digest])))[0]
    mean_digest = summed / nw
    assert abs(mean_digest - digest) < 1e-5 * max(1.0, abs(digest)), (
        f"rank {rank}: params diverged: {digest} vs mean {mean_digest}"
    )
    kv.barrier()
    print(f"rank {rank}/{nw} DIST-TRAIN OK acc={acc:.3f}", flush=True)


if __name__ == "__main__":
    main()
