"""Broad finite-difference gradient + dtype sweep over the op library.

Models the reference's ``tests/python/unittest/test_operator.py`` (3228 LoC)
methodology: every differentiable op family gets central-difference gradient
checks against the analytic backward (``check_numeric_gradient``,
reference test_utils.py:470), plus bf16-vs-f32 forward consistency for the
families that run in mixed precision on the MXU.

Parametrized: ~170 gradient checks across unary math, binary/broadcast,
reductions, shape/index ops, and NN layers in multiple configs.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (
    assert_almost_equal,
    check_numeric_gradient,
    check_symbolic_forward,
)

_rng = np.random.RandomState(7)


def _pos(shape, lo=0.5, hi=2.0):
    return _rng.uniform(lo, hi, shape).astype(np.float32)


def _smooth(shape, scale=1.0):
    """Values kept away from kinks (|x| > 0.15) so FD is stable."""
    x = _rng.uniform(0.2, 1.0, shape) * _rng.choice([-1, 1], shape)
    return (x * scale).astype(np.float32)


# --------------------------------------------------------------------------
# unary math ops: (name, data generator, tolerance override)
# --------------------------------------------------------------------------
_UNARY = [
    ("sigmoid", _smooth, {}),
    ("tanh", _smooth, {}),
    ("exp", _smooth, {}),
    ("log", _pos, {}),
    ("log10", _pos, {}),
    ("log2", _pos, {}),
    ("log1p", _pos, {}),
    ("expm1", _smooth, {}),
    ("sqrt", _pos, {}),
    ("rsqrt", _pos, {}),
    ("cbrt", _pos, {}),
    ("rcbrt", _pos, {}),
    ("square", _smooth, {}),
    ("abs", _smooth, {}),
    ("negative", _smooth, {}),
    ("reciprocal", _pos, {}),
    ("sin", _smooth, {}),
    ("cos", _smooth, {}),
    ("tan", lambda s: _smooth(s, 0.5), {}),
    ("arcsin", lambda s: _smooth(s, 0.5), {}),
    ("arccos", lambda s: _smooth(s, 0.5), {}),
    ("arctan", _smooth, {}),
    ("sinh", _smooth, {}),
    ("cosh", _smooth, {}),
    ("arcsinh", _smooth, {}),
    ("arccosh", lambda s: _pos(s, 1.5, 3.0), {}),
    ("arctanh", lambda s: _smooth(s, 0.5), {}),
    ("erf", _smooth, {}),
    ("gamma", lambda s: _pos(s, 1.2, 3.0), {"rtol": 0.05, "atol": 1e-2}),
    ("gammaln", lambda s: _pos(s, 1.2, 3.0), {"rtol": 0.05, "atol": 1e-2}),
    ("softsign", _smooth, {}),
    ("degrees", _smooth, {"rtol": 0.05}),
    ("radians", _smooth, {}),
    ("relu", _smooth, {}),
    ("identity", _smooth, {}),
    ("smooth_l1", lambda s: _smooth(s, 2.0), {}),
]


@pytest.mark.parametrize("name,gen,tol", _UNARY, ids=[u[0] for u in _UNARY])
def test_unary_grad(name, gen, tol):
    data = mx.sym.Variable("data")
    sym = getattr(mx.sym, name)(data)
    check_numeric_gradient(sym, {"data": gen((3, 4))}, **tol)


@pytest.mark.parametrize("name,gen,tol", _UNARY[:12], ids=[u[0] for u in _UNARY[:12]])
def test_unary_bf16_forward(name, gen, tol):
    """bf16 forward agrees with f32 at bf16 resolution (MXU dtype sweep)."""
    x = gen((3, 4))
    f32 = getattr(mx.nd, name)(mx.nd.array(x)).asnumpy()
    b16 = getattr(mx.nd, name)(mx.nd.array(x, dtype="bfloat16")).asnumpy()
    assert_almost_equal(b16.astype(np.float32), f32, rtol=2e-2, atol=2e-2)


# --------------------------------------------------------------------------
# binary elemwise + broadcast
# --------------------------------------------------------------------------
_BINARY = ["elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div"]


@pytest.mark.parametrize("name", _BINARY)
def test_binary_grad(name):
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = getattr(mx.sym, name)(a, b)
    check_numeric_gradient(
        sym, {"a": _smooth((3, 4)), "b": _pos((3, 4))}
    )


_BROADCAST = [
    ("broadcast_add", False),
    ("broadcast_sub", False),
    ("broadcast_mul", False),
    ("broadcast_div", True),
    ("broadcast_maximum", False),
    ("broadcast_minimum", False),
    ("broadcast_hypot", False),
    ("broadcast_power", True),
]


@pytest.mark.parametrize("name,positive", _BROADCAST, ids=[b[0] for b in _BROADCAST])
@pytest.mark.parametrize("bshape", [(1, 4), (3, 1)], ids=["row", "col"])
def test_broadcast_grad(name, positive, bshape):
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = getattr(mx.sym, name)(a, b)
    gen = _pos if positive else _smooth
    av, bv = gen((3, 4)), gen(bshape)
    if name in ("broadcast_maximum", "broadcast_minimum"):
        # disjoint ranges: FD at a min/max tie straddles the kink
        av, bv = _pos((3, 4), 0.2, 0.9), _pos(bshape, 1.2, 1.9)
    check_numeric_gradient(sym, {"a": av, "b": bv}, rtol=2e-2, atol=1e-3)


def test_broadcast_compare_forward():
    a = np.array([[1, 2], [3, 4]], np.float32)
    b = np.array([[2], [3]], np.float32)
    for name, op in [("broadcast_equal", np.equal),
                     ("broadcast_not_equal", np.not_equal),
                     ("broadcast_greater", np.greater),
                     ("broadcast_greater_equal", np.greater_equal),
                     ("broadcast_lesser", np.less),
                     ("broadcast_lesser_equal", np.less_equal)]:
        got = getattr(mx.nd, name)(mx.nd.array(a), mx.nd.array(b)).asnumpy()
        assert_almost_equal(got, op(a, b).astype(np.float32))


# --------------------------------------------------------------------------
# reductions over axis combinations
# --------------------------------------------------------------------------
_REDUCE = ["sum", "mean", "prod", "nansum", "nanprod"]
_AXES = [None, 0, 1, (0, 2)]


@pytest.mark.parametrize("name", _REDUCE)
@pytest.mark.parametrize("axis", _AXES, ids=["all", "ax0", "ax1", "ax02"])
@pytest.mark.parametrize("keepdims", [False, True], ids=["nokeep", "keep"])
def test_reduce_grad(name, axis, keepdims):
    data = mx.sym.Variable("data")
    kwargs = {"keepdims": keepdims}
    if axis is not None:
        kwargs["axis"] = axis
    sym = getattr(mx.sym, name)(data, **kwargs)
    check_numeric_gradient(sym, {"data": _pos((2, 3, 4))}, rtol=2e-2, atol=1e-3)


@pytest.mark.parametrize("name", ["max", "min"])
@pytest.mark.parametrize("axis", [None, 1], ids=["all", "ax1"])
def test_minmax_reduce_grad(name, axis):
    data = mx.sym.Variable("data")
    kwargs = {} if axis is None else {"axis": axis}
    sym = getattr(mx.sym, name)(data, **kwargs)
    # well-separated values so the argmax is FD-stable
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    x = x[..., _rng.permutation(4)] * 0.7
    check_numeric_gradient(sym, {"data": x})


def test_norm_grad():
    data = mx.sym.Variable("data")
    check_numeric_gradient(mx.sym.norm(data), {"data": _smooth((3, 4))})


# --------------------------------------------------------------------------
# shape / slicing / assembly ops
# --------------------------------------------------------------------------
def test_reshape_grad():
    data = mx.sym.Variable("data")
    for target in [(4, 6), (2, -1), (0, -1), (-2,), (2, 2, 6)]:
        sym = mx.sym.Reshape(data, shape=target)
        check_numeric_gradient(sym, {"data": _smooth((2, 3, 4))})


def test_transpose_grad():
    data = mx.sym.Variable("data")
    for axes in [None, (1, 0, 2), (2, 0, 1)]:
        sym = mx.sym.transpose(data) if axes is None else mx.sym.transpose(data, axes=axes)
        check_numeric_gradient(sym, {"data": _smooth((2, 3, 4))})


def test_swapaxis_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.SwapAxis(data, dim1=0, dim2=2)
    check_numeric_gradient(sym, {"data": _smooth((2, 3, 4))})


@pytest.mark.parametrize("spec", [
    dict(begin=(0, 1), end=(2, 3)),
    dict(begin=(1, 0), end=(2, 4)),
])
def test_slice_grad(spec):
    data = mx.sym.Variable("data")
    sym = mx.sym.slice(data, **spec)
    check_numeric_gradient(sym, {"data": _smooth((3, 4))})


def test_slice_axis_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.slice_axis(data, axis=1, begin=1, end=3)
    check_numeric_gradient(sym, {"data": _smooth((3, 4))})


def test_flip_reverse_grad():
    data = mx.sym.Variable("data")
    check_numeric_gradient(mx.sym.flip(data, axis=1), {"data": _smooth((3, 4))})
    check_numeric_gradient(mx.sym.reverse(data, axis=0), {"data": _smooth((3, 4))})


def test_tile_repeat_grad():
    data = mx.sym.Variable("data")
    check_numeric_gradient(mx.sym.tile(data, reps=(2, 3)), {"data": _smooth((2, 2))})
    check_numeric_gradient(mx.sym.repeat(data, repeats=2, axis=1),
                           {"data": _smooth((2, 3))})


def test_pad_grad_modes():
    data = mx.sym.Variable("data")
    for mode in ["constant", "edge", "reflect"]:
        sym = mx.sym.Pad(data, mode=mode, pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
                         constant_value=0.0)
        check_numeric_gradient(sym, {"data": _smooth((1, 2, 4, 4))})


def test_concat_split_grad():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = mx.sym.Concat(a, b, dim=1)
    check_numeric_gradient(sym, {"a": _smooth((2, 3)), "b": _smooth((2, 2))})
    data = mx.sym.Variable("data")
    outs = mx.sym.SliceChannel(data, num_outputs=2, axis=1)
    check_numeric_gradient(outs[0] + outs[1] * 2, {"data": _smooth((2, 4))})


def test_stack_expand_dims_grad():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    check_numeric_gradient(mx.sym.stack(a, b, axis=1),
                           {"a": _smooth((2, 3)), "b": _smooth((2, 3))})
    data = mx.sym.Variable("data")
    check_numeric_gradient(mx.sym.expand_dims(data, axis=1),
                           {"data": _smooth((2, 3))})


def test_where_grad():
    cond = np.array([[1, 0, 1], [0, 1, 0]], np.float32)
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    c = mx.sym.Variable("c")
    sym = mx.sym.where(c, a, b)
    check_numeric_gradient(
        sym, {"c": cond, "a": _smooth((2, 3)), "b": _smooth((2, 3))},
        grad_nodes=["a", "b"],
    )


def test_clip_grad_interior():
    data = mx.sym.Variable("data")
    sym = mx.sym.clip(data, a_min=-10, a_max=10)  # interior: acts as identity
    check_numeric_gradient(sym, {"data": _smooth((3, 4))})


# --------------------------------------------------------------------------
# indexing ops
# --------------------------------------------------------------------------
def test_take_grad():
    w = mx.sym.Variable("w")
    idx = mx.sym.Variable("idx")
    sym = mx.sym.take(w, idx)
    check_numeric_gradient(
        sym, {"w": _smooth((5, 3)), "idx": np.array([0, 2, 2, 4], np.float32)},
        grad_nodes=["w"],
    )


def test_embedding_grad():
    data = mx.sym.Variable("data")
    weight = mx.sym.Variable("weight")
    sym = mx.sym.Embedding(data=data, weight=weight, input_dim=6, output_dim=3)
    check_numeric_gradient(
        sym, {"data": np.array([1, 3, 3], np.float32), "weight": _smooth((6, 3))},
        grad_nodes=["weight"],
    )


def test_pick_gather_forward():
    x = _smooth((3, 4))
    idx = np.array([0, 2, 1], np.float32)
    got = mx.nd.pick(mx.nd.array(x), mx.nd.array(idx)).asnumpy()
    assert_almost_equal(got, x[np.arange(3), idx.astype(int)])
    nd = mx.nd.batch_take(mx.nd.array(x), mx.nd.array(idx))
    assert_almost_equal(nd.asnumpy(), x[np.arange(3), idx.astype(int)])


def test_one_hot_forward():
    got = mx.nd.one_hot(mx.nd.array([0, 2, 1]), depth=4).asnumpy()
    assert_almost_equal(got, np.eye(4, dtype=np.float32)[[0, 2, 1]])


# --------------------------------------------------------------------------
# matrix ops
# --------------------------------------------------------------------------
@pytest.mark.parametrize("ta", [False, True], ids=["a", "aT"])
@pytest.mark.parametrize("tb", [False, True], ids=["b", "bT"])
def test_dot_grad_transposes(ta, tb):
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = mx.sym.dot(a, b, transpose_a=ta, transpose_b=tb)
    sa = (4, 3) if ta else (3, 4)
    sb = (5, 4) if tb else (4, 5)
    check_numeric_gradient(sym, {"a": _smooth(sa), "b": _smooth(sb)})


@pytest.mark.parametrize("ta", [False, True], ids=["a", "aT"])
@pytest.mark.parametrize("tb", [False, True], ids=["b", "bT"])
def test_batch_dot_grad_transposes(ta, tb):
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = mx.sym.batch_dot(a, b, transpose_a=ta, transpose_b=tb)
    sa = (2, 4, 3) if ta else (2, 3, 4)
    sb = (2, 5, 4) if tb else (2, 4, 5)
    check_numeric_gradient(sym, {"a": _smooth(sa), "b": _smooth(sb)})


# --------------------------------------------------------------------------
# NN layers in several configs
# --------------------------------------------------------------------------
@pytest.mark.parametrize("flatten", [True])
@pytest.mark.parametrize("no_bias", [False, True], ids=["bias", "nobias"])
def test_fc_grad(flatten, no_bias):
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data, num_hidden=4, no_bias=no_bias, name="fc")
    loc = {"data": _smooth((2, 3, 2)), "fc_weight": _smooth((4, 6))}
    if not no_bias:
        loc["fc_bias"] = _smooth((4,))
    check_numeric_gradient(sym, loc)


_CONV_CASES = [
    dict(kernel=(3, 3), pad=(1, 1)),
    dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1)),
    dict(kernel=(1, 1)),
    dict(kernel=(3, 3), dilate=(2, 2), pad=(2, 2)),
    dict(kernel=(3, 3), pad=(1, 1), num_group=2),
    dict(kernel=(3, 3), pad=(1, 1), no_bias=True),
]


@pytest.mark.parametrize("case", _CONV_CASES,
                         ids=["3x3", "s2", "1x1", "dil2", "grp2", "nobias"])
def test_conv_grad_cases(case):
    data = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data, num_filter=4, name="c", **case)
    ng = case.get("num_group", 1)
    loc = {
        "data": _smooth((2, 2, 7, 7)),
        "c_weight": _smooth((4, 2 // ng) + case["kernel"]),
    }
    if not case.get("no_bias"):
        loc["c_bias"] = _smooth((4,))
    check_numeric_gradient(sym, loc, rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("case", [
    dict(kernel=(2, 2), stride=(2, 2)),
    dict(kernel=(3, 3), stride=(1, 1), pad=(1, 1)),
], ids=["s2", "s1pad"])
def test_deconv_grad_cases(case):
    data = mx.sym.Variable("data")
    sym = mx.sym.Deconvolution(data, num_filter=3, no_bias=True, name="d", **case)
    loc = {
        "data": _smooth((2, 2, 4, 4)),
        "d_weight": _smooth((2, 3) + case["kernel"]),
    }
    check_numeric_gradient(sym, loc, rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("pool_type", ["max", "avg", "sum"])
@pytest.mark.parametrize("global_pool", [False, True], ids=["win", "global"])
def test_pooling_grad(pool_type, global_pool):
    data = mx.sym.Variable("data")
    sym = mx.sym.Pooling(
        data, kernel=(2, 2), stride=(2, 2), pool_type=pool_type,
        global_pool=global_pool,
    )
    x = _rng.permutation(np.arange(64, dtype=np.float32)).reshape(1, 4, 4, 4)
    check_numeric_gradient(sym, {"data": x * 0.3}, rtol=2e-2, atol=2e-3)


@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "softrelu"])
def test_activation_grad_types(act):
    data = mx.sym.Variable("data")
    sym = mx.sym.Activation(data, act_type=act)
    check_numeric_gradient(sym, {"data": _smooth((3, 4))})


@pytest.mark.parametrize("act", ["leaky", "elu"])
def test_leaky_relu_grad_types(act):
    data = mx.sym.Variable("data")
    sym = mx.sym.LeakyReLU(data, act_type=act, slope=0.3)
    check_numeric_gradient(sym, {"data": _smooth((3, 4))})


def test_prelu_grad():
    data = mx.sym.Variable("data")
    gamma = mx.sym.Variable("gamma")
    sym = mx.sym.LeakyReLU(data, gamma=gamma, act_type="prelu")
    check_numeric_gradient(
        sym, {"data": _smooth((3, 4)), "gamma": _pos((4,), 0.1, 0.4)}
    )


def test_batchnorm_grad():
    data = mx.sym.Variable("data")
    gamma = mx.sym.Variable("gamma")
    beta = mx.sym.Variable("beta")
    sym = mx.sym.BatchNorm(data, gamma, beta, fix_gamma=False, eps=1e-3,
                           name="bn")
    check_numeric_gradient(
        sym,
        {"data": _smooth((4, 3, 2, 2)), "gamma": _pos((3,)), "beta": _smooth((3,))},
        aux_states={"bn_moving_mean": np.zeros(3, np.float32),
                    "bn_moving_var": np.ones(3, np.float32)},
        rtol=3e-2, atol=3e-3,
    )


def test_instance_norm_grad():
    data = mx.sym.Variable("data")
    gamma = mx.sym.Variable("gamma")
    beta = mx.sym.Variable("beta")
    sym = mx.sym.InstanceNorm(data, gamma, beta, eps=1e-3)
    check_numeric_gradient(
        sym,
        {"data": _smooth((2, 3, 4)), "gamma": _pos((3,)), "beta": _smooth((3,))},
        rtol=3e-2, atol=3e-3,
    )


def test_l2_normalization_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.L2Normalization(data, eps=1e-6)
    check_numeric_gradient(sym, {"data": _smooth((3, 4))}, rtol=2e-2)


def test_lrn_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.LRN(data, nsize=3, alpha=1e-3, beta=0.75, knorm=2.0)
    check_numeric_gradient(sym, {"data": _smooth((2, 5, 3, 3))}, rtol=2e-2)


def test_softmax_grad():
    data = mx.sym.Variable("data")
    check_numeric_gradient(mx.sym.softmax(data), {"data": _smooth((3, 4))})
    check_numeric_gradient(mx.sym.log_softmax(data), {"data": _smooth((3, 4))})


def test_softmax_axis0_grad():
    data = mx.sym.Variable("data")
    check_numeric_gradient(mx.sym.softmax(data, axis=0), {"data": _smooth((3, 4))})


def test_upsampling_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.UpSampling(data, scale=2, sample_type="nearest")
    check_numeric_gradient(sym, {"data": _smooth((1, 2, 3, 3))})


def test_sequence_ops_grad():
    data = mx.sym.Variable("data")
    length = np.array([2, 3], np.float32)
    x = _smooth((3, 2, 4))  # (seq, batch, feat)
    sym = mx.sym.SequenceLast(data, mx.sym.Variable("len"),
                              use_sequence_length=True)
    check_numeric_gradient(sym, {"data": x, "len": length}, grad_nodes=["data"])
    sym = mx.sym.SequenceMask(data, mx.sym.Variable("len"),
                              use_sequence_length=True, value=0.0)
    check_numeric_gradient(sym, {"data": x, "len": length}, grad_nodes=["data"])
    sym = mx.sym.SequenceReverse(data, mx.sym.Variable("len"),
                                 use_sequence_length=True)
    check_numeric_gradient(sym, {"data": x, "len": length}, grad_nodes=["data"])


def test_crop_grad():
    data = mx.sym.Variable("data")
    sym = mx.sym.Crop(data, offset=(1, 1), h_w=(2, 2), center_crop=False)
    check_numeric_gradient(sym, {"data": _smooth((1, 2, 4, 4))})


def test_roipooling_forward():
    x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = mx.nd.ROIPooling(
        mx.nd.array(x), mx.nd.array(rois), pooled_size=(2, 2), spatial_scale=1.0
    ).asnumpy()
    assert out.shape == (1, 2, 2, 2)
    assert_almost_equal(out[0, 0], [[5, 7], [13, 15]])


# --------------------------------------------------------------------------
# ordering + misc forward correctness
# --------------------------------------------------------------------------
def test_ordering_forward():
    x = _smooth((3, 5))
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.sort(nd).asnumpy(), np.sort(x, axis=-1))
    assert_almost_equal(
        mx.nd.argsort(nd).asnumpy().astype(int), np.argsort(x, axis=-1, kind="stable")
    )
    k = 2
    topv = mx.nd.topk(nd, k=k, ret_typ="value").asnumpy()
    assert_almost_equal(topv, -np.sort(-x, axis=-1)[:, :k])
    assert_almost_equal(
        mx.nd.argmax(nd, axis=1).asnumpy(), np.argmax(x, axis=1).astype(np.float32)
    )
    assert_almost_equal(
        mx.nd.argmin(nd, axis=1).asnumpy(), np.argmin(x, axis=1).astype(np.float32)
    )


def test_rounding_forward():
    x = np.array([-1.7, -0.5, 0.2, 1.5, 2.5], np.float32)
    assert_almost_equal(mx.nd.floor(mx.nd.array(x)).asnumpy(), np.floor(x))
    assert_almost_equal(mx.nd.ceil(mx.nd.array(x)).asnumpy(), np.ceil(x))
    assert_almost_equal(mx.nd.trunc(mx.nd.array(x)).asnumpy(), np.trunc(x))
    assert_almost_equal(mx.nd.fix(mx.nd.array(x)).asnumpy(), np.fix(x))
    assert_almost_equal(mx.nd.sign(mx.nd.array(x)).asnumpy(), np.sign(x))


def test_cast_dtypes():
    x = _smooth((2, 3))
    for dt in ["float16", "bfloat16", "int32", "uint8"]:
        got = mx.nd.Cast(mx.nd.array(np.abs(x) * 10), dtype=dt)
        assert str(got.dtype) == dt


def test_loss_layer_grads():
    """Loss layers define their own backward (FGradient ignores head grads),
    so they're checked against the closed forms, not finite differences."""
    x = _smooth((3, 4))
    y = _smooth((3, 4))
    n = x.shape[1]  # reference normalizes by per-sample output count

    def analytic(sym_fn, data, label):
        data_s = mx.sym.Variable("data")
        label_s = mx.sym.Variable("label")
        sym = sym_fn(data_s, label_s)
        exe = sym.bind(
            mx.cpu(),
            args={"data": mx.nd.array(data), "label": mx.nd.array(label)},
            args_grad={"data": mx.nd.zeros(data.shape)},
            grad_req={"data": "write", "label": "null"},
        )
        exe.forward(is_train=True)
        exe.backward()
        return exe.grad_dict["data"].asnumpy()

    g = analytic(mx.sym.LinearRegressionOutput, x, y)
    assert_almost_equal(g, (x - y) / n, rtol=1e-4, atol=1e-5)
    g = analytic(mx.sym.MAERegressionOutput, x + 3, y)
    assert_almost_equal(g, np.sign(x + 3 - y) / n, rtol=1e-4, atol=1e-5)
    lbl = np.abs(np.sign(y))
    g = analytic(mx.sym.LogisticRegressionOutput, x, lbl)
    sig = 1 / (1 + np.exp(-x))
    assert_almost_equal(g, (sig - lbl) / n, rtol=1e-4, atol=1e-5)


def test_makeloss_grad_scale():
    data = mx.sym.Variable("data")
    sym = mx.sym.MakeLoss(mx.sym.square(data), grad_scale=2.0)
    x = _smooth((3, 4))
    exe = sym.bind(mx.cpu(), args={"data": mx.nd.array(x)},
                   args_grad={"data": mx.nd.zeros((3, 4))})
    exe.forward(is_train=True)
    exe.backward()
    assert_almost_equal(exe.grad_dict["data"].asnumpy(), 4.0 * x, rtol=1e-4)


def test_elementwise_sum_grad():
    syms = [mx.sym.Variable(n) for n in "abc"]
    sym = mx.sym.ElementWiseSum(*syms)
    check_numeric_gradient(
        sym, {n: _smooth((2, 3)) for n in "abc"}
    )


def test_dropout_eval_identity_train_scale():
    data = mx.sym.Variable("data")
    sym = mx.sym.Dropout(data, p=0.5)
    x = _pos((50, 50))
    exe = sym.bind(mx.cpu(), args={"data": mx.nd.array(x)})
    exe.forward(is_train=False)
    assert_almost_equal(exe.outputs[0].asnumpy(), x)
    exe.forward(is_train=True)
    out = exe.outputs[0].asnumpy()
    kept = out != 0
    assert 0.3 < kept.mean() < 0.7
    assert_almost_equal(out[kept], (x / 0.5)[kept], rtol=1e-5)
