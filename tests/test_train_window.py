"""Training-window tests: K fused steps in one program == K serial steps.

The window is the TPU answer to dispatch-bound training loops (the
reference's engine pipelines per-op pushes asynchronously,
``src/engine/threaded_engine.cc``; a jit boundary can't pipeline across
executes on dispatch-latency-bound runtimes, so the window moves the loop
INTO the program — see ``Executor.fused_train_update`` ``n_steps``).
"""

import numpy as np
import pytest

import mxnet_tpu as mx


def _sym():
    d = mx.sym.Variable("data")
    l = mx.sym.Variable("softmax_label")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.BatchNorm(h, name="bn1")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, label=l, name="softmax")


def _module(opt="sgd", opt_params=None):
    m = mx.mod.Module(_sym(), context=mx.cpu())
    m.bind(data_shapes=[mx.io.DataDesc("data", (8, 32))],
           label_shapes=[mx.io.DataDesc("softmax_label", (8,))])
    m.init_params(initializer=mx.init.Xavier(), force_init=True)
    m.init_optimizer(
        optimizer=opt,
        optimizer_params=opt_params or {"learning_rate": 0.1, "momentum": 0.9},
    )
    return m


def _batches(n=4, seed=3):
    rng = np.random.RandomState(seed)
    return [
        mx.io.DataBatch(
            data=[mx.nd.array(rng.randn(8, 32))],
            label=[mx.nd.array(rng.randint(0, 10, (8,)))],
        )
        for _ in range(n)
    ]


class _WindowSpy:
    """Records every fused_train_update dispatch's n_steps (proves the
    window actually ran fused rather than falling back serially)."""

    def __init__(self, monkeypatch):
        from mxnet_tpu.executor import Executor

        self.calls = []
        orig = Executor.fused_train_update

        def spy(exe, *a, **kw):
            self.calls.append(kw.get("n_steps", 1))
            return orig(exe, *a, **kw)

        monkeypatch.setattr(Executor, "fused_train_update", spy)


def _assert_params_equal(m_ref, m_win, rtol=2e-5, atol=2e-5):
    a1, x1 = m_ref.get_params()
    a2, x2 = m_win.get_params()
    for k in a1:
        np.testing.assert_allclose(
            a1[k].asnumpy(), a2[k].asnumpy(), rtol=rtol, atol=atol, err_msg=k
        )
    for k in x1:  # aux (BN moving stats) must advance per-iteration too
        np.testing.assert_allclose(
            x1[k].asnumpy(), x2[k].asnumpy(), rtol=rtol, atol=atol, err_msg=k
        )


def test_stacked_batches_window_matches_serial(monkeypatch):
    bs = _batches(4)
    mx.random.seed(7)
    m_ref = _module()
    mx.random.seed(7)
    m_win = _module()
    for b in bs:
        m_ref.forward_backward(b)
        m_ref.update()
    spy = _WindowSpy(monkeypatch)
    m_win.train_window(None, batches=bs)
    assert spy.calls == [4], "window fell back to serial dispatch"
    _assert_params_equal(m_ref, m_win)


def test_same_batch_window_matches_serial_and_outputs(monkeypatch):
    bs = _batches(1)
    mx.random.seed(7)
    m_ref = _module()
    mx.random.seed(7)
    m_win = _module()
    for _ in range(5):
        m_ref.forward_backward(bs[0])
        m_ref.update()
    spy = _WindowSpy(monkeypatch)
    m_win.train_window(bs[0], n_steps=5)
    assert spy.calls == [5], "window fell back to serial dispatch"
    _assert_params_equal(m_ref, m_win)
    np.testing.assert_allclose(
        m_ref.get_outputs()[0].asnumpy(),
        m_win.get_outputs()[0].asnumpy(), rtol=2e-5, atol=2e-5,
    )


def test_window_advances_update_count_and_t():
    bs = _batches(1)
    mx.random.seed(7)
    m = _module()
    m.train_window(bs[0], n_steps=3)
    assert m._optimizer.num_update == 3
    # a following single step continues the count seamlessly
    m.forward_backward(bs[0])
    m.update()
    assert m._optimizer.num_update == 4


def test_window_momentum_optimizer_state_advances():
    """Optimizer state (momentum) after a window equals serial-state."""
    bs = _batches(3, seed=11)
    mx.random.seed(9)
    m_ref = _module()
    mx.random.seed(9)
    m_win = _module()
    for b in bs:
        m_ref.forward_backward(b)
        m_ref.update()
    m_win.train_window(None, batches=bs)
    s_ref = m_ref._updater.states
    s_win = m_win._updater.states
    assert set(s_ref) == set(s_win)
    for k in s_ref:
        r, w = s_ref[k], s_win[k]
        if r is None:
            assert w is None
            continue
        np.testing.assert_allclose(
            np.asarray(r.asnumpy()), np.asarray(w.asnumpy()),
            rtol=2e-5, atol=2e-5,
        )


def test_window_falls_back_without_traceable_optimizer(monkeypatch):
    """When the step can't run as one program the window loops serially."""
    bs = _batches(2)
    mx.random.seed(5)
    m_ref = _module()
    mx.random.seed(5)
    m_win = _module()
    monkeypatch.setattr(type(m_win._optimizer), "jax_apply", None)
    monkeypatch.setattr(type(m_ref._optimizer), "jax_apply", None)
    for b in bs:
        m_ref.forward_backward(b)
        m_ref.update()
    spy = _WindowSpy(monkeypatch)
    m_win.train_window(None, batches=bs)
    assert spy.calls == []  # nothing fusable: pure serial fallback
    _assert_params_equal(m_ref, m_win)


def test_window_rng_stream_continues_into_serial_steps(monkeypatch):
    """Stochastic ops must not replay window-consumed rng streams.

    A window of 3 + 2 serial steps must consume the same per-step dropout
    masks as 5 serial steps (the host step counter advances by the window
    length, not by 1)."""
    def _sym_do():
        d = mx.sym.Variable("data")
        l = mx.sym.Variable("softmax_label")
        h = mx.sym.FullyConnected(d, num_hidden=32, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.Dropout(h, p=0.5, name="do1")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
        return mx.sym.SoftmaxOutput(h, label=l, name="softmax")

    def _make():
        m = mx.mod.Module(_sym_do(), context=mx.cpu())
        m.bind(data_shapes=[mx.io.DataDesc("data", (8, 32))],
               label_shapes=[mx.io.DataDesc("softmax_label", (8,))])
        m.init_params(initializer=mx.init.Xavier(), force_init=True)
        m.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1})
        return m

    b = _batches(1)[0]
    mx.random.seed(13)
    m_ref = _make()
    for _ in range(5):
        m_ref.forward_backward(b)
        m_ref.update()
    mx.random.seed(13)
    m_win = _make()
    spy = _WindowSpy(monkeypatch)
    m_win.train_window(b, n_steps=3)
    for _ in range(2):
        m_win.forward_backward(b)
        m_win.update()
    assert spy.calls[0] == 3
    _assert_params_equal(m_ref, m_win)


def test_window_stacks_cast_to_bound_dtype(monkeypatch):
    """f32 batches fed to a bf16-bound module follow _bind_inputs' cast:
    the window trains the same trajectory as serial steps."""
    def _make():
        m = mx.mod.Module(_sym(), context=mx.cpu())
        m.bind(data_shapes=[mx.io.DataDesc("data", (8, 32), "bfloat16")],
               label_shapes=[mx.io.DataDesc("softmax_label", (8,))])
        m.init_params(initializer=mx.init.Xavier(), force_init=True)
        m.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1})
        return m

    bs = _batches(3)
    mx.random.seed(21)
    m_ref = _make()
    mx.random.seed(21)
    m_win = _make()
    for b in bs:
        m_ref.forward_backward(b)
        m_ref.update()
    spy = _WindowSpy(monkeypatch)
    m_win.train_window(None, batches=bs)
    assert spy.calls == [3]
    import jax.numpy as jnp

    exe = m_win._exec_group._exec
    assert exe.arg_dict["data"]._data.dtype == jnp.bfloat16
    _assert_params_equal(m_ref, m_win, rtol=5e-3, atol=5e-3)  # bf16 path


def test_window_hyper_tape_starts_at_first_step(monkeypatch):
    """The program's t tape and lr are the WINDOW-START values (t advances
    on-device; lr is frozen for the window)."""
    from mxnet_tpu.executor import Executor

    seen = {}
    orig = Executor.fused_train_update

    def spy(exe, names, fn, states, lrs, wds, ts, **kw):
        seen["ts"] = list(ts)
        seen["lrs"] = list(lrs)
        return orig(exe, names, fn, states, lrs, wds, ts, **kw)

    import pytest

    mp = pytest.MonkeyPatch()
    mp.setattr(Executor, "fused_train_update", spy)
    try:
        mx.random.seed(2)
        m = _module(opt_params={
            "learning_rate": 0.4,
            "lr_scheduler": mx.lr_scheduler.FactorScheduler(step=2,
                                                            factor=0.5),
        })
        m.train_window(_batches(1)[0], n_steps=4)
    finally:
        mp.undo()
    assert all(t == 1 for t in seen["ts"])  # first step of the window
    assert all(abs(lr - 0.4) < 1e-9 for lr in seen["lrs"])  # un-decayed
    assert m._optimizer.num_update == 4  # host count lands on window end


def test_window_grad_add_falls_back_serial(monkeypatch):
    """grad_req='add' modules get the documented serial fallback (no
    mid-flight executor error)."""
    mx.random.seed(5)
    m = mx.mod.Module(_sym(), context=mx.cpu())
    m.bind(data_shapes=[mx.io.DataDesc("data", (8, 32))],
           label_shapes=[mx.io.DataDesc("softmax_label", (8,))],
           grad_req="add")
    m.init_params(initializer=mx.init.Xavier(), force_init=True)
    m.init_optimizer(optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1})
    spy = _WindowSpy(monkeypatch)
    m.train_window(_batches(1)[0], n_steps=3)
    assert all(k == 1 for k in spy.calls)  # serial single-step dispatches


def test_window_unbound_label_and_empty_batches():
    """Labels carried by batches but not bound by the symbol are dropped
    (serial-feed semantics); an empty batches list is a no-op."""
    import warnings

    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=4, name="fc")
    out = mx.sym.MakeLoss(mx.sym.sum(h * h), name="loss")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = mx.mod.Module(out, context=mx.cpu())  # default label_names
    m.bind(data_shapes=[mx.io.DataDesc("data", (4, 8))], label_shapes=None)
    m.init_params(force_init=True)
    m.init_optimizer(optimizer="sgd",
                     optimizer_params={"learning_rate": 0.01})
    m.train_window(None, batches=[])  # no-op, no crash
    rng = np.random.RandomState(0)
    bs = [mx.io.DataBatch(data=[mx.nd.array(rng.randn(4, 8))],
                          label=[mx.nd.array(rng.randn(4,))])
          for _ in range(3)]
    m.train_window(None, batches=bs)  # must not raise on the stray label


def test_window_rejects_grad_add():
    m = mx.mod.Module(_sym(), context=mx.cpu())
    m.bind(data_shapes=[mx.io.DataDesc("data", (8, 32))],
           label_shapes=[mx.io.DataDesc("softmax_label", (8,))],
           grad_req="add")
    m.init_params(initializer=mx.init.Xavier(), force_init=True)
    m.init_optimizer(optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1})
    b = _batches(1)[0]
    # schedule one backward so grads accumulate, then a window must refuse
    m.forward_backward(b)
    m.update()
    m.forward(b, is_train=True)
    m.backward()
    with pytest.raises(mx.base.MXNetError):
        m._exec_group.update_fused(
            m._optimizer,
            m._updater if not m._update_on_kvstore else m._kvstore._updater,
            n_steps=4,
        )


def test_window_bad_stack_shape_rejected():
    m = _module()
    b = _batches(1)[0]
    m.forward(b, is_train=True)
    m.backward()
    with pytest.raises(mx.base.MXNetError):
        m._exec_group.update_fused(
            m._optimizer,
            m._updater if not m._update_on_kvstore else m._kvstore._updater,
            n_steps=4,
            data_stacks={"data": mx.nd.zeros((4, 9, 32))},
        )


def test_window_checkpoint_resume_exact(tmp_path):
    """save_checkpoint + optimizer states after windows resume EXACTLY:
    window(3)+save / load+window(2) == window(5) trajectories."""
    bs = _batches(1, seed=17)
    prefix = str(tmp_path / "winck")
    mx.random.seed(23)
    m1 = _module()
    m1.train_window(bs[0], n_steps=3)
    m1.save_checkpoint(prefix, 3, save_optimizer_states=True)
    m1.train_window(bs[0], n_steps=2)

    sym, args, auxs = mx.model.load_checkpoint(prefix, 3)
    m2 = mx.mod.Module(sym, context=mx.cpu())
    m2.bind(data_shapes=[mx.io.DataDesc("data", (8, 32))],
            label_shapes=[mx.io.DataDesc("softmax_label", (8,))])
    m2.set_params(args, auxs)
    m2.init_optimizer(optimizer="sgd",
                      optimizer_params={"learning_rate": 0.1,
                                        "momentum": 0.9})
    m2.load_optimizer_states(prefix + "-0003.states")
    m2.train_window(bs[0], n_steps=2)
    _assert_params_equal(m1, m2)


def test_window_publish_grads_false_same_params_grads_raise(monkeypatch):
    """A no-publish window trains IDENTICALLY (the gradient publication is
    output-only — dead-coding it cannot change the update math), returns a
    WindowBoundary whose grads() raises, and leaves grad_dict raising
    loudly instead of serving a stale step's values."""
    bs = _batches(3, seed=5)
    mx.random.seed(9)
    m_pub = _module()
    mx.random.seed(9)
    m_lazy = _module()
    spy = _WindowSpy(monkeypatch)
    b_pub = m_pub.train_window(None, batches=bs)
    b_lazy = m_lazy.train_window(None, batches=bs, publish_grads=False)
    assert spy.calls == [3, 3], "a window fell back to serial dispatch"
    _assert_params_equal(m_pub, m_lazy, rtol=0, atol=0)  # bitwise
    np.testing.assert_array_equal(
        b_pub.outputs[0].asnumpy(), b_lazy.outputs[0].asnumpy())
    # published boundary serves gradients; lazy boundary refuses
    assert "fc1_weight" in b_pub.grads()
    with pytest.raises(mx.base.MXNetError, match="publish_grads"):
        b_lazy.grads()
    with pytest.raises(mx.base.MXNetError, match="not published"):
        m_lazy._exec_group._exec.grad_dict["fc1_weight"].asnumpy()
    # metadata stays queryable without materializing (fit's prepare path
    # and shape introspection must not blow up on unpublished handles)
    g = m_lazy._exec_group._exec.grad_dict["fc1_weight"]
    assert g.shape == m_lazy._exec_group._exec.arg_dict["fc1_weight"].shape
    # the next publishing step heals the handles
    m_lazy.forward_backward(bs[0])
    m_lazy.update()
    assert np.isfinite(
        m_lazy._exec_group._exec.grad_dict["fc1_weight"].asnumpy()).all()


def test_window_boundary_wait_and_serial_fallback(monkeypatch):
    """WindowBoundary.wait() retires the window (chainable), and the
    serial fallback honors publish_grads both ways: True snapshots the
    boundary gradients, False skips the per-window snapshot (the
    pipelined fit loop would discard it) while grad_dict itself keeps
    the serial loop's real values."""
    bs = _batches(2, seed=8)
    m = _module()
    b = m.train_window(None, batches=bs, publish_grads=False)
    assert b.wait() is b and b.n_steps == 2
    # empty windows return no boundary
    assert m.train_window(None, batches=[]) is None
    # force the serial fallback (non-traceable optimizer)
    m2 = _module()
    m2._optimizer.jax_apply = None
    spy = _WindowSpy(monkeypatch)
    b2 = m2.train_window(None, batches=bs, publish_grads=False)
    assert spy.calls == [], "serial fallback dispatched a fused window"
    assert b2 is not None and b2.n_steps == 2
    assert b2.wait() is b2
    with pytest.raises(mx.base.MXNetError, match="publish_grads"):
        b2.grads()
    # the serial loop still leaves real values on the live handles
    assert np.isfinite(
        m2._exec_group._exec.grad_dict["fc1_weight"].asnumpy()).all()
    # and the default (publish_grads=True) serves a snapshotted boundary
    b3 = m2.train_window(None, batches=bs)
    assert "fc1_weight" in b3.grads()
