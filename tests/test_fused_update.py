"""Fused train-step equivalence: the single donated fwd+bwd+update XLA
program (Executor.fused_train_update) must produce the same parameters and
optimizer state as the imperative per-param updater path it replaces
(reference semantics: Updater over src/operator/optimizer_op.cc kernels).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym_mod


def _mlp():
    data = sym_mod.Variable("data")
    net = sym_mod.FullyConnected(data, name="fc1", num_hidden=16)
    net = sym_mod.Activation(net, name="relu1", act_type="relu")
    net = sym_mod.FullyConnected(net, name="fc2", num_hidden=4)
    return sym_mod.SoftmaxOutput(net, name="softmax")


def _train(optimizer, optimizer_params, n_steps=5, force_legacy=False,
           seed=7):
    mx.random.seed(42)  # identical init across the two runs
    rng = np.random.RandomState(seed)
    xs = rng.randn(n_steps, 8, 10).astype(np.float32)
    ys = rng.randint(0, 4, (n_steps, 8)).astype(np.float32)

    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer=optimizer, optimizer_params=optimizer_params)
    if force_legacy:
        # disabling the traceable update forces the per-param updater path
        mod._optimizer.jax_apply = None
    for i in range(n_steps):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(xs[i])], label=[mx.nd.array(ys[i])]
        )
        mod.forward_backward(batch)
        mod.update()
    args, _ = mod.get_params()
    states = mod._updater.states if mod._updater is not None else {}
    return {k: v.asnumpy() for k, v in args.items()}, states


@pytest.mark.parametrize("opt,params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.1, "clip_gradient": 0.5}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-4, "clip_gradient": 1.0}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05, "wd": 1e-4}),
    ("ftrl", {"learning_rate": 0.1}),
    ("adadelta", {}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
])
def test_fused_matches_imperative(opt, params):
    fused, _ = _train(opt, params)
    legacy, _ = _train(opt, params, force_legacy=True)
    assert fused.keys() == legacy.keys()
    for k in fused:
        np.testing.assert_allclose(
            fused[k], legacy[k], rtol=2e-5, atol=2e-6,
            err_msg=f"{opt}: param {k} diverged between fused and "
                    "imperative update paths",
        )


def test_fused_state_roundtrips_through_updater(tmp_path):
    """Optimizer state written by the fused path must serialize/reload via
    the Updater exactly like the imperative path (checkpoint parity)."""
    rng = np.random.RandomState(3)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(8, 10).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))],
    )
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    fname = str(tmp_path / "opt.states")
    mod.save_optimizer_states(fname)
    # momentum must be non-trivial (the fused path actually wrote state)
    states = mod._updater.states
    assert any(
        st is not None and float(np.abs(st.asnumpy()).sum()) > 0
        for st in states.values()
    )
    mod.load_optimizer_states(fname)
    mod.forward_backward(batch)
    mod.update()  # still trains after reload


def test_forward_after_backward_preserves_ordering():
    """forward() scheduled after a deferred backward() must not be clobbered
    when the backward materialises: engine write-ordering (reference
    threaded_engine read/write sequencing)."""
    rng = np.random.RandomState(11)
    exe_sym = _mlp()
    mod = mx.mod.Module(exe_sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    d1 = mx.nd.array(rng.randn(4, 10).astype(np.float32))
    d2 = mx.nd.array(rng.randn(4, 10).astype(np.float32))
    lab = mx.nd.array(np.zeros(4, np.float32))
    exe = mod._exec_group._exec
    # train fwd+bwd on batch 1 (deferred), then eval fwd on batch 2
    exe.forward(is_train=True, data=d1._data, softmax_label=lab._data)
    exe.backward()
    out2 = exe.forward(is_train=False, data=d2._data, softmax_label=lab._data)
    got = out2[0].asnumpy()
    # reference: outputs must be batch-2's eval forward, not batch-1's
    exe2 = mod._exec_group._exec
    arg_vals, arg_flat = exe2._arg_vals_split()
    arg_vals = [d2._data if n == "data" else v
                for n, v in zip(exe2.arg_names, arg_vals)]
    aux_vals, aux_flat = exe2._aux_vals_split()
    ref = np.asarray(
        exe2._get_jit("forward", is_train=False)(
            arg_vals, arg_flat, aux_vals, aux_flat, exe2._rng_key(),
        )[0][0]
    )
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # and batch-1's gradients must still have been computed
    g = exe.grad_dict["fc1_weight"].asnumpy()
    assert np.abs(g).sum() > 0


def test_fused_update_with_monitor_falls_back():
    """Installing a monitor materialises grads eagerly; update() must fall
    back to the imperative path and still converge (no pending backward)."""
    rng = np.random.RandomState(5)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(8, 10).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))],
    )
    mod.forward_backward(batch)
    # reading a gradient consumes the scheduled backward
    g = mod._exec_group._exec.grad_dict["fc1_weight"].asnumpy()
    assert np.isfinite(g).all()
    before = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy().copy()
    mod.update()  # falls back; must still apply the update
    after = mod._exec_group._exec.arg_dict["fc1_weight"].asnumpy()
    assert not np.allclose(before, after)
