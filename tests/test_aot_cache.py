"""AOT subsystem tests: persistent executable cache contract, warmup APIs,
and the adaptive train-window scheduler.

The cache contract is the PR's acceptance bar: populate the cache
(tools/aot_warm.py), spawn a FRESH process, and the reload must bind + run
the bench-model family with ``executor.jit_compile == 0`` — every
steady-state program deserializes instead of recompiling. Serialization
tests carry the ``aot_serialization`` marker; conftest skips them on
backends that cannot serialize executables.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import aot
import mxnet_tpu.telemetry as tm

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _subprocess_env(cache_dir):
    """JAX_PLATFORMS=cpu + axon env scrubbed (the established pattern:
    a leaked axon pool address makes any spawned jax-initialising child
    dial the chip — 300s hang mode) + the AOT cache pointed at tmp."""
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_AOT_CACHE"] = "1"
    env["MXNET_AOT_CACHE_DIR"] = str(cache_dir)
    return env


@pytest.mark.aot_serialization
def test_persistent_cache_fresh_process_zero_compiles(tmp_path):
    """aot_warm populates the cache for the bench-model family; a fresh
    process then binds + runs forward/train-step/fused-update with
    executor.jit_compile == 0 and aot.cache_hit > 0."""
    env = _subprocess_env(tmp_path / "aot")
    warm = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "aot_warm.py"),
         "resnet", "--data-shape", "2,3,32,32",
         "--model-arg", "num_classes=10", "--model-arg", "num_layers=18",
         "--model-arg", "image_shape=3,32,32", "--step"],
        capture_output=True, text=True, env=env, timeout=600, cwd=_ROOT,
    )
    assert warm.returncode == 0, warm.stderr[-2000:]
    cache_files = os.listdir(tmp_path / "aot")
    assert len(cache_files) >= 3, cache_files  # fwd eval/train + step + fused

    reload = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tests", "aot_cache_worker.py")],
        capture_output=True, text=True, env=env, timeout=600, cwd=_ROOT,
    )
    assert reload.returncode == 0, reload.stderr[-2000:]
    rec = json.loads(reload.stdout.strip().splitlines()[-1])
    assert rec["jit_compile"] == 0, rec  # warm start: XLA never ran
    assert rec["cache_hit"] >= 3, rec   # train_step + fused + eval forward
    assert rec["deserialize_error"] == 0, rec
    assert rec["grad_norm"] > 0 and rec["out_shape"] == [2, 10], rec


@pytest.mark.aot_serialization
def test_aot_warm_cli_smoke(tmp_path):
    """The warm CLI runs standalone on a tiny zoo model, populates the
    cache dir, and a second invocation is all hits (idempotent)."""
    env = _subprocess_env(tmp_path / "aot")
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "aot_warm.py"),
           "mlp", "--data-shape", "4,784", "--model-arg", "num_classes=10",
           "--step"]
    first = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=600, cwd=_ROOT)
    assert first.returncode == 0, first.stderr[-2000:]
    assert "stores=4" in first.stdout, first.stdout
    n_files = len(os.listdir(tmp_path / "aot"))
    assert n_files >= 4
    second = subprocess.run(cmd, capture_output=True, text=True, env=env,
                            timeout=600, cwd=_ROOT)
    assert second.returncode == 0, second.stderr[-2000:]
    assert "hits=4" in second.stdout, second.stdout
    assert len(os.listdir(tmp_path / "aot")) == n_files  # nothing re-stored


@pytest.mark.aot_serialization
def test_corrupt_cache_entry_recompiles(tmp_path, monkeypatch):
    """A corrupt cache file reads as a miss (deserialize_error counted,
    entry removed) and the program recompiles + re-persists."""
    monkeypatch.setenv("MXNET_AOT_CACHE", "1")
    monkeypatch.setenv("MXNET_AOT_CACHE_DIR", str(tmp_path))
    d = aot.digest("probe-corrupt")
    path = os.path.join(aot.cache_dir(), d + ".aotx")
    os.makedirs(aot.cache_dir(), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"not a pickle")
    errs = tm.counter("aot.deserialize_error").value
    assert aot.load(d) is None
    assert tm.counter("aot.deserialize_error").value == errs + 1
    assert not os.path.exists(path)  # poisoned entry evicted

    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x * 3).lower(jnp.ones((2,))).compile()
    assert aot.store(d, compiled)
    loaded = aot.load(d)
    assert loaded is not None
    np.testing.assert_allclose(np.asarray(loaded(jnp.ones((2,)))), 3.0)


def _mlp_module(batch=8):
    d = mx.sym.Variable("data")
    l = mx.sym.Variable("softmax_label")
    h = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=10, name="fc2"), label=l,
        name="softmax")
    m = mx.mod.Module(net, context=mx.cpu())
    m.bind(data_shapes=[mx.io.DataDesc("data", (batch, 32))],
           label_shapes=[mx.io.DataDesc("softmax_label", (batch,))])
    m.init_params(initializer=mx.init.Xavier(), force_init=True)
    return m


def test_module_compile_warms_all_programs():
    """Module.compile pre-builds forward/forward_train/train_step; the
    subsequent first steps are all in-memory executable hits (no further
    XLA compiles)."""
    m = _mlp_module()
    tm.reset()
    kinds = m.compile()
    assert kinds == ["forward", "forward_train", "train_step"]
    compiles = tm.counter("executor.jit_compile").value
    assert compiles == 3
    rng = np.random.RandomState(0)
    b = mx.io.DataBatch(data=[mx.nd.array(rng.randn(8, 32))],
                        label=[mx.nd.array(rng.randint(0, 10, (8,)))])
    m.forward(b, is_train=True)
    m.backward()
    _ = m._exec_group._exec.grad_dict["fc1_weight"].asnumpy()
    m.forward(b, is_train=False)
    _ = m.get_outputs()[0].asnumpy()
    assert tm.counter("executor.jit_compile").value == compiles
    assert tm.counter("executor.jit_cache_hit").value >= 2


def test_bucketing_compile_warms_buckets_in_parallel():
    """BucketingModule.compile binds + pre-compiles the given bucket set
    (thread pool; XLA compilation releases the GIL); running each bucket
    afterwards triggers no new jit compiles."""
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data, input_dim=10, output_dim=6, name="emb")
        pooled = mx.sym.sum(emb, axis=1)
        net = mx.sym.FullyConnected(pooled, num_hidden=4, name="fc")
        return mx.sym.SoftmaxOutput(net, name="softmax"), ("data",), \
            ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    compiled = mod.compile(
        buckets=[(4, [("data", (4, 4))], [("softmax_label", (4,))])])
    assert set(compiled) == {8, 4}
    assert all("forward" in kinds for kinds in compiled.values())
    tm.reset()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    for key, dshape in [(8, (4, 8)), (4, (4, 4))]:
        batch = mx.io.DataBatch(
            data=[mx.nd.ones(dshape)], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", dshape)],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))],
        )
        mod.forward(batch, is_train=False)
        _ = mod.get_outputs()[0].asnumpy()
    assert tm.counter("executor.jit_compile").value == 0
    assert tm.counter("executor.jit_cache_hit").value >= 2


# --- adaptive train-window scheduler ---------------------------------------

def test_choose_train_window_dispatch_bound_picks_deep_window():
    # synthetic dispatch-bound profile: 3 ms dispatch vs 0.5 ms residual
    k = aot.choose_train_window(3000.0, 500.0)
    assert k >= 2
    # fully dispatch-bound (no residual at all): cap at max_k
    assert aot.choose_train_window(3000.0, 0.0, max_k=32) == 32


def test_choose_train_window_device_bound_stays_serial():
    # device/data-bound: dispatch is a rounding error next to the residual
    assert aot.choose_train_window(100.0, 40000.0) == 1
    assert aot.choose_train_window(0.0, 1000.0) == 1


def test_scheduler_auto_decides_from_synthetic_telemetry():
    """TrainWindowScheduler('auto') probes single-step, then locks K from
    the fit.* histograms: dispatch-bound profiles get K >= 2,
    device-bound ones stay at 1."""
    def run(dispatch_us, data_wait_us):
        tm.reset()
        sched = aot.TrainWindowScheduler("auto")
        skip = sched.SKIP_BATCHES
        probe = sched.PROBE_BATCHES
        for _i in range(skip + probe):
            assert sched.next_k() == 1  # probing single-step
            tm.histogram("fit.dispatch").observe(dispatch_us)
            tm.histogram("fit.data_wait").observe(data_wait_us)
            sched.observe(1)
        return sched.next_k()

    assert run(dispatch_us=3000, data_wait_us=300) >= 2
    assert run(dispatch_us=100, data_wait_us=40000) == 1
    assert tm.gauge("fit.train_window_k").value == 1  # decision published


def test_scheduler_restarts_probe_on_partial_telemetry_reset():
    """A telemetry reset mid-probe (bench's compile-epoch reset) can leave
    the dispatch delta positive but a residual delta negative; the
    scheduler must restart the probe instead of reading residual<=0 as
    'fully dispatch-bound' and locking max_k on a device-bound loop."""
    tm.reset()
    sched = aot.TrainWindowScheduler("auto")
    for _ in range(sched.SKIP_BATCHES):
        sched.next_k()
        tm.histogram("fit.dispatch").observe(100)
        tm.histogram("fit.data_wait").observe(40000)
        sched.observe(1)
    sched.next_k()  # takes the rebase
    for _ in range(sched.PROBE_BATCHES):
        tm.histogram("fit.dispatch").observe(100)
        tm.histogram("fit.data_wait").observe(40000)
        sched.observe(1)
    # simulate the mid-probe reset: data_wait loses its accumulated sum
    tm.histogram("fit.data_wait")._zero()
    tm.histogram("fit.dispatch")._zero()
    for _ in range(3):  # dispatch count recovers past the base, sum low
        tm.histogram("fit.dispatch").observe(100)
    assert sched.next_k() == 1          # probe restarted, not K=max
    assert not sched._decided


def test_scheduler_fixed_setting_and_env_parse(monkeypatch):
    assert aot.TrainWindowScheduler(4).next_k() == 4
    monkeypatch.setenv("MXNET_TRAIN_WINDOW", "auto")
    assert aot.train_window_setting() == "auto"
    monkeypatch.setenv("MXNET_TRAIN_WINDOW", "8")
    assert aot.train_window_setting() == 8
    for off in ("", "0", "1", "none", "garbage"):
        monkeypatch.setenv("MXNET_TRAIN_WINDOW", off)
        assert aot.train_window_setting() is None


def test_choose_dispatch_depth_profiles():
    # double buffering is the baseline whenever windows engage
    assert aot.choose_dispatch_depth(500.0, 3000.0) == 2
    # dispatch-dominated host loop (tunnel round trips): one extra window
    # of slack absorbs host-time bursts
    assert aot.choose_dispatch_depth(3000.0, 500.0) == 3
    assert aot.choose_dispatch_depth(3000.0, 500.0, max_depth=2) == 2
    # no profile at all: still double-buffer
    assert aot.choose_dispatch_depth(0.0, 0.0) == 2


def test_dispatch_depth_env_parse(monkeypatch):
    monkeypatch.delenv("MXNET_DISPATCH_DEPTH", raising=False)
    assert aot.dispatch_depth_setting() == "auto"
    monkeypatch.setenv("MXNET_DISPATCH_DEPTH", "auto")
    assert aot.dispatch_depth_setting() == "auto"
    monkeypatch.setenv("MXNET_DISPATCH_DEPTH", "3")
    assert aot.dispatch_depth_setting() == 3
    monkeypatch.setenv("MXNET_DISPATCH_DEPTH", "0")
    assert aot.dispatch_depth_setting() == 1  # floor: a depth must exist
    monkeypatch.setenv("MXNET_DISPATCH_DEPTH", "junk")
    assert aot.dispatch_depth_setting() == "auto"


def test_scheduler_co_tunes_k_and_depth(monkeypatch):
    """Auto scheduling resolves (K, depth) together from the probe: a
    dispatch-bound profile gets deep-ish windows AND depth >= 2, with K
    SMALLER than the unpipelined choice (the in-flight overlap already
    hides the round trip); device-bound stays (1, 1). cap_depth forces a
    fenced pipeline and says why."""
    monkeypatch.delenv("MXNET_DISPATCH_DEPTH", raising=False)

    def run(dispatch_us, data_wait_us):
        tm.reset()
        sched = aot.TrainWindowScheduler("auto")
        for _i in range(sched.SKIP_BATCHES + sched.PROBE_BATCHES):
            sched.next_k()
            tm.histogram("fit.dispatch").observe(dispatch_us)
            tm.histogram("fit.data_wait").observe(data_wait_us)
            sched.observe(1)
        return sched.next_k(), sched

    k, sched = run(dispatch_us=3000, data_wait_us=300)
    assert k >= 2 and sched.depth >= 2
    assert tm.gauge("fit.dispatch_depth").value == sched.depth
    assert k <= aot.choose_train_window(3000, 300)  # co-tuned K relaxes
    k1, sched1 = run(dispatch_us=100, data_wait_us=40000)
    assert k1 == 1 and sched1.depth == 1
    # policy cap: depth 1, reason recorded, gauge says so
    k2, sched2 = run(dispatch_us=3000, data_wait_us=300)
    sched2.cap_depth("nonfinite-rollback")
    assert sched2.depth == 1
    assert sched2.depth_cap_reason == "nonfinite-rollback"
    assert tm.gauge("fit.dispatch_depth").value == 1
    # a fixed env depth is honored without a probe
    monkeypatch.setenv("MXNET_DISPATCH_DEPTH", "3")
    assert aot.TrainWindowScheduler(4).depth == 3
    # ...but K=1 means no windows: a fixed depth must not make the gauge
    # claim a pipeline the per-batch loop cannot deliver
    k3, sched3 = run(dispatch_us=100, data_wait_us=40000)
    assert k3 == 1 and sched3.depth == 1
    assert tm.gauge("fit.dispatch_depth").value == 1


def test_fit_with_fixed_window_matches_serial_trajectory(monkeypatch):
    """MXNET_TRAIN_WINDOW=K in fit dispatches train_window chunks and
    trains the same trajectory as the per-batch loop."""
    from mxnet_tpu.executor import Executor

    monkeypatch.delenv("MXNET_TRAIN_WINDOW", raising=False)
    rng = np.random.RandomState(3)
    data = rng.randn(32, 32).astype(np.float32)
    label = rng.randint(0, 10, (32,)).astype(np.float32)

    def fit_one():
        m = _mlp_module()
        it = mx.io.NDArrayIter(data, label, batch_size=8,
                               label_name="softmax_label")
        m.fit(it, num_epoch=2, eval_metric="acc",
              initializer=mx.init.Xavier(),
              optimizer_params={"learning_rate": 0.1})
        return m

    mx.random.seed(11)
    m_ref = fit_one()

    calls = []
    orig = Executor.fused_train_update

    def spy(exe, *a, **kw):
        calls.append(kw.get("n_steps", 1))
        return orig(exe, *a, **kw)

    monkeypatch.setattr(Executor, "fused_train_update", spy)
    monkeypatch.setenv("MXNET_TRAIN_WINDOW", "4")
    mx.random.seed(11)
    m_win = fit_one()
    assert 4 in calls, f"no window dispatch: {calls}"
    a_ref, x_ref = m_ref.get_params()
    a_win, x_win = m_win.get_params()
    for k in a_ref:
        np.testing.assert_allclose(a_ref[k].asnumpy(), a_win[k].asnumpy(),
                                   rtol=2e-5, atol=2e-5, err_msg=k)


def test_aot_program_falls_back_on_exec_mismatch():
    """An AOTProgram whose executable rejects the arguments permanently
    falls back to the jit path (never a user-visible failure)."""
    import jax
    import jax.numpy as jnp

    prog = aot.AOTProgram(jax.jit(lambda x: x + 1))
    np.testing.assert_allclose(np.asarray(prog(jnp.ones((2,)))), 2.0)
    assert prog.executable is not None
    base = tm.counter("aot.exec_fallback").value
    # different shape: the compiled executable rejects it, jit re-traces
    np.testing.assert_allclose(np.asarray(prog(jnp.ones((3, 3)))), 2.0)
    assert tm.counter("aot.exec_fallback").value == base + 1
    # and stays on the jit path from then on
    np.testing.assert_allclose(np.asarray(prog(jnp.ones((2,)))), 2.0)
