"""Symbol tests (reference test_symbol.py, test_attr.py, test_infer_shape.py)."""

import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=10)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_compose_and_listing():
    net = _mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label",
    ]
    assert net.list_outputs() == ["softmax_output"]
    assert net.name == "softmax"


def test_auto_naming():
    with mx.name.NameManager():
        a = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=3)
        b = mx.sym.FullyConnected(a, num_hidden=3)
        assert a.name == "fullyconnected0"
        assert b.name == "fullyconnected1"


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 20))
    assert arg_shapes == [(8, 20), (10, 20), (10,), (4, 10), (4,), (8,)]
    assert out_shapes == [(8, 4)]
    assert aux_shapes == []


def test_infer_shape_partial():
    net = _mlp()
    arg_shapes, out_shapes, _ = net.infer_shape_partial()
    assert arg_shapes[0] is None
    # conv tower partial: only data known halfway
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=2)
    arg_shapes, _, _ = conv.infer_shape(data=(1, 3, 8, 8))
    assert arg_shapes[1] == (2, 3, 3, 3)


def test_infer_type():
    net = _mlp()
    arg_types, out_types, _ = net.infer_type(data="float32")
    assert all(t == np.float32 for t in arg_types)
    assert out_types[0] == np.float32


def test_symbol_group_and_index():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    g = mx.sym.Group([a + b, a * b])
    assert len(g.list_outputs()) == 2
    first = g[0]
    assert len(first.list_outputs()) == 1


def test_get_internals():
    net = _mlp()
    internals = net.get_internals()
    outs = internals.list_outputs()
    assert "fc1_output" in outs
    assert "data" in outs
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = mx.sym.fromjson(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    assert net2.tojson() == js
    with tempfile.TemporaryDirectory() as td:
        fname = os.path.join(td, "sym.json")
        net.save(fname)
        net3 = mx.sym.load(fname)
        assert net3.list_arguments() == net.list_arguments()


def test_symbol_attrs():
    data = mx.sym.Variable("data", lr_mult=2.0)
    with mx.AttrScope(ctx_group="dev1"):
        fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    assert fc.attr("ctx_group") == "dev1"
    attrs = fc.attr_dict()
    assert attrs["data"]["__lr_mult__"] == "2.0"
    assert attrs["fc"]["ctx_group"] == "dev1"


def test_variable_shape_attr():
    v = mx.sym.Variable("x", shape=(3, 4))
    fc = mx.sym.FullyConnected(v, num_hidden=2)
    arg_shapes, out_shapes, _ = fc.infer_shape()
    assert arg_shapes[0] == (3, 4)
    assert out_shapes[0] == (3, 2)


def test_multi_output_indexing():
    x = mx.sym.Variable("x")
    parts = mx.sym.SliceChannel(x, num_outputs=3, name="split")
    assert len(parts.list_outputs()) == 3
    p1 = parts[1]
    out = p1.eval(ctx=mx.cpu(), x=mx.nd.array(np.arange(9).reshape(1, 9)))
    assert out[0].shape == (1, 3)
    np.testing.assert_allclose(out[0].asnumpy(), [[3, 4, 5]])


def test_infer_shape_mismatch_raises():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = fc + mx.sym.FullyConnected(data, num_hidden=4, name="fc2")
    with pytest.raises(MXNetError):
        net.infer_shape(data=(2, 5))


def test_arithmetic_sugar():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    x = np.array([2.0, 4.0], dtype=np.float32)
    y = np.array([3.0, 5.0], dtype=np.float32)
    for sym, expected in [
        (a + b, x + y), (a - b, x - y), (a * b, x * y), (a / b, x / y),
        (a + 1.0, x + 1), (2.0 * a, 2 * x), (1.0 / a, 1 / x), (a ** 2.0, x ** 2),
        (a > b, (x > y).astype(np.float32)),
        (a <= b, (x <= y).astype(np.float32)),
    ]:
        exe = sym.bind(mx.cpu(), args={"a": mx.nd.array(x), "b": mx.nd.array(y)} if "b" in sym.list_arguments() else {"a": mx.nd.array(x)})
        exe.forward()
        np.testing.assert_allclose(exe.outputs[0].asnumpy(), expected, rtol=1e-6)
