"""bench.py and __graft_entry__ must always run: the driver executes both
at round end, and a crash there loses the round's headline numbers."""

import json
import os
import subprocess
import sys
import tempfile

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_cpu_smoke():
    env = dict(os.environ)
    # the axon site dir re-pins JAX_PLATFORMS at interpreter startup;
    # drop it so the cpu override sticks (tests must not touch the chip)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_LAYERS"] = "18"
    env["BENCH_ITERS"] = "3"
    env["BENCH_WINDOWS"] = "2"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, timeout=900, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = r.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["unit"] == "images/sec" and rec["value"] > 0
    assert "cpusmoke" in rec["metric"]
    # the non-finite guard's cost stays visible in every BENCH_*.json
    assert "nonfinite_guard_overhead" in rec
    assert rec["guard_on_img_per_sec"] > 0
    # guard overhead pin, pipelining enabled (windows dispatch with lazy
    # boundary publication): the chip bar is < 2% and is recorded by the
    # BENCH trajectory; this tiny-model CPU smoke measures the same loop
    # with +/-6% host noise (observed), so the pin here is the
    # noise-tolerant band that still catches a structural regression — a
    # guard that re-grew a per-batch sync or fence costs 2x, not 15%
    assert rec["nonfinite_guard_overhead"] < 0.15, rec


def test_bench_fit_mode_reaches_window_rate():
    """BENCH_MODE=fit (real NDArrayIter + Accuracy via Module.fit) must run
    at >=90% of the synthetic train_window throughput on the same config —
    the async-pipeline acceptance bar (device prefetch + device metrics
    leave no per-batch host sync on the fit path)."""
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_LAYERS"] = "18"
    env["BENCH_BATCH"] = "4"
    env["BENCH_ITERS"] = "4"
    # 3 timed windows/epochs per mode: the reported value is a median, so a
    # single host hiccup in one window can't sink the comparison
    env["BENCH_WINDOWS"] = "3"
    # the guard-overhead re-measure is test_bench_cpu_smoke's job; here it
    # would only stretch the train-mode run this comparison waits on
    env["BENCH_GUARD"] = "0"
    # kernel attribution is pinned by the guard-on test; the profiled
    # window would only stretch this throughput comparison
    env["BENCH_KERNELS"] = "0"

    def run(mode):
        e = dict(env)
        e["BENCH_MODE"] = mode
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "bench.py")],
            capture_output=True, text=True, env=e, timeout=900, cwd=_ROOT,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    fit = run("fit")
    assert "fit" in fit["metric"]
    window = run("train")
    fit_rate = fit["value"]
    if fit_rate < 0.9 * window["value"]:
        # shared-host noise guard: one re-measure before declaring a
        # pipeline regression
        fit_rate = max(fit_rate, run("fit")["value"])
    assert fit_rate >= 0.9 * window["value"], (
        f"fit loop at {fit_rate} img/s vs train_window "
        f"{window['value']} img/s — async pipeline regressed")


def test_bench_fit_guard_on_keeps_no_sync_invariant():
    """With MXNET_NONFINITE_GUARD=skip AND pipelined window dispatch, the
    fit loop's steady-state telemetry (embedded in the bench record) must
    show ZERO host-blocking syncs — the guard's skip decision lives on
    device and never reads back per batch — and the guard must NOT cap
    the pipeline: dispatch depth stays >= 2 (the gauge) with >= 2 windows
    actually observed in flight. Only the rollback/raise policies may
    fence to depth 1 (documented boundary-fence taxonomy)."""
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_LAYERS"] = "18"
    env["BENCH_BATCH"] = "4"
    env["BENCH_ITERS"] = "4"
    env["BENCH_WINDOWS"] = "2"
    env["BENCH_MODE"] = "fit"
    env["BENCH_WARM_START"] = "0"
    env["MXNET_NONFINITE_GUARD"] = "skip"
    env["MXNET_TRAIN_WINDOW"] = "2"
    env["MXNET_DISPATCH_DEPTH"] = "2"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, timeout=900, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    nd = rec["telemetry"].get("ndarray", {})
    assert nd.get("asnumpy", 0) == 0, rec["telemetry"]
    assert nd.get("wait_to_read", 0) == 0, rec["telemetry"]
    metric = rec["telemetry"].get("metric", {})
    assert metric.get("numpy_fallback", 0) == 0, rec["telemetry"]
    # pipelined dispatch pins (cpu-smoke fit mode): configured depth on
    # the gauge, achieved depth on the in-flight high-water mark, and the
    # JSON tail fields the trajectory reads
    fit = rec["telemetry"].get("fit", {})
    assert fit.get("dispatch_depth", {}).get("value", 0) >= 2, rec
    assert fit.get("windows_in_flight", {}).get("max", 0) >= 2, rec
    assert fit.get("window", {}).get("count", 0) >= 2, rec
    assert rec.get("dispatch_depth", 0) >= 2, rec
    assert rec.get("train_window_k", 0) == 2, rec
    assert 0 < rec.get("dispatch_span_share", 0) <= 1, rec
    # device-side attribution contract (ISSUE 18): every fit record names
    # its conv layout + precision recipe and embeds the top-10 per-kernel
    # device-time table (attributed AFTER the timed region)
    assert rec["layout"] in ("NCHW", "NHWC"), rec
    assert rec["recipe"] in ("f32", "bf16_master"), rec
    kernels = rec["kernels"]
    assert 0 < len(kernels) <= 10, kernels
    total_pct = 0.0
    for row in kernels:
        assert row["name"] and row["device_us"] > 0 and row["calls"] >= 1
        assert 0 <= row["pct"] <= 1
        total_pct += row["pct"]
    assert total_pct <= 1.0 + 1e-6, kernels
    # sorted by device time, heaviest first
    assert all(a["device_us"] >= b["device_us"]
               for a, b in zip(kernels, kernels[1:])), kernels


def test_bench_serve_mode_beats_sequential_and_never_compiles():
    """BENCH_MODE=serve: the dynamic batcher under concurrent synthetic
    load must (a) reach at least the batch-size-1 sequential predictor
    throughput — batching that loses to no batching is a regression —
    and (b) perform ZERO XLA compiles on the request path (the embedded
    telemetry snapshot's executor.jit_compile / aot counters cover the
    whole traffic window; every bucket executable was warmed up front)."""
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_MODE"] = "serve"
    env["BENCH_LAYERS"] = "18"
    env["BENCH_SERVE_CLIENTS"] = "6"
    env["BENCH_SERVE_REQUESTS"] = "8"
    env["BENCH_SERVE_SEQ_ITERS"] = "6"

    def run():
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "bench.py")],
            capture_output=True, text=True, env=env, timeout=900,
            cwd=_ROOT,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    rec = run()
    assert "serving_throughput" in rec["metric"]
    assert rec["errors"] == 0
    assert rec["value"] > 0 and rec["p99_ms"] >= rec["p50_ms"] > 0
    # no-request-path-compile invariant: the snapshot covers traffic only
    ex = rec["telemetry"].get("executor", {})
    assert ex.get("jit_compile", 0) == 0, rec["telemetry"]
    aot = rec["telemetry"].get("aot", {})
    assert aot.get("trace_compile", 0) == 0, rec["telemetry"]
    assert rec["telemetry"]["serving"]["batches"] > 0
    rate = rec["value"]
    if rate < rec["sequential_img_per_sec"]:
        # shared-host noise guard: one re-measure before failing — the
        # retry stands on its own (its value vs its OWN sequential
        # baseline; mixing runs could pass when both individually failed)
        rec = run()
        rate = rec["value"]
    assert rate >= rec["sequential_img_per_sec"], (
        f"batcher at {rate} img/s lost to sequential batch-1 "
        f"{rec['sequential_img_per_sec']} img/s")


def test_bench_serve_sharded_legs_no_compile_and_curve():
    """BENCH_SERVE_SHARDED=1 on the virtual 8-device CPU mesh: every
    mesh leg (tp2 / pp2 / dp-of-tp2) serves with ZERO request-path
    compiles and zero errors, dp-of-tp2 actually fans out to 4 group
    replicas, and the tp2 scaling curve is reported at 1/2/4 groups.
    (The curve's SLOPE is the TPU round's acceptance — virtual CPU
    devices share host cores, so only structure is pinned here.)"""
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["BENCH_MODE"] = "serve"
    env["BENCH_LAYERS"] = "18"
    env["BENCH_SERVE_CLIENTS"] = "4"
    env["BENCH_SERVE_REQUESTS"] = "6"
    env["BENCH_SERVE_SEQ_ITERS"] = "2"
    env["BENCH_SERVE_SCALING"] = "0"
    env["BENCH_SERVE_SHARDED"] = "1"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, timeout=900, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    sharded = rec["sharded"]
    for name in ("tp2", "pp2", "dp-tp2"):
        leg = sharded[name]
        assert leg["errors"] == 0, (name, leg)
        assert leg["request_path_compiles"] == 0, (name, leg)
        assert leg["img_per_sec"] > 0, (name, leg)
        assert leg["p99_ms"] > 0, (name, leg)
    assert sharded["tp2"]["replicas"] == 1
    assert sharded["pp2"]["replicas"] == 1
    assert sharded["dp-tp2"]["replicas"] == 4
    curve = sharded["tp2_scaling_curve"]
    assert sorted(curve) == ["1", "2", "4"]
    assert all(v > 0 for v in curve.values()), curve
    assert sharded["group_scaling_4x"] > 0


def test_bench_serve_chaos_availability():
    """BENCH_CHAOS=1 serve leg: a replica killed under concurrent traffic
    and later revived must cost availability NOTHING (failover absorbs
    it) — pinned >= 0.99 per the serving SLO — with the fault window's
    p99 reported and at least one counted failover re-dispatch."""
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    # >= 2 virtual devices so the pool has a survivor to fail over to
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    env["BENCH_MODE"] = "serve"
    env["BENCH_CHAOS"] = "1"
    env["BENCH_LAYERS"] = "18"
    env["BENCH_SERVE_BUCKETS"] = "1,4"
    env["BENCH_SERVE_CLIENTS"] = "4"
    env["BENCH_SERVE_REQUESTS"] = "6"
    env["BENCH_SERVE_SEQ_ITERS"] = "2"
    env["BENCH_SERVE_SCALING"] = "0"  # scaling leg is the TPU round's job
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, timeout=900, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["replicas"] == 2
    assert rec["errors"] == 0  # the clean measurement phase
    assert rec["availability"] >= 0.99, rec["chaos"]
    assert rec["chaos"]["failed"] == 0, rec["chaos"]
    assert rec["chaos"]["failover_count"] >= 1, (
        "replica kill never exercised failover")
    assert rec["p99_during_fault_ms"] > 0
    # both replicas actually served during the clean phase
    assert all(v > 0 for v in rec["per_replica_batches"].values()), rec


def _bench_env(**overrides):
    env = dict(os.environ)
    clean = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_ROOT] + clean)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(overrides)
    return env


def _run_bench(env):
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py")],
        capture_output=True, text=True, env=env, timeout=900, cwd=_ROOT,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])


_SUITE_SMOKE_KNOBS = {
    "BENCH_MODE": "suite",
    # trimmed timed region: the smoke pins are structural (presence,
    # steady_compiles==0 counter-verified, finite outputs) — per-workload
    # compile time dominates this leg regardless of window count, and the
    # tier-1 wall budget pays for it once, here (the bf16-no-NaN pin
    # lives in test_whole_zoo_fastpath.py where it costs seconds, not a
    # second bf16 compile of every trunk)
    "BENCH_SUITE_WINDOWS": "2",
    "BENCH_SUITE_WARMUP": "1",
    "BENCH_SUITE_INFER_ITERS": "1",
}

_SUITE_WORKLOADS = ("mlp", "lenet", "resnet-50", "lstm-ptb", "ssd-vgg16",
                    "dcgan")


def test_bench_suite_whole_zoo_smoke():
    """BENCH_MODE=suite: EVERY BASELINE workload must appear in the one
    scoreboard record with the fast-path invariants intact — zero
    steady-state compiles (the counters bench embeds per workload), finite
    training outputs, per-symbol FLOPs populated — and the DCGAN fused
    window must beat the reference imperative loop."""
    rec = _run_bench(_bench_env(**_SUITE_SMOKE_KNOBS))
    assert "whole_zoo_suite" in rec["metric"]
    assert "cpusmoke" in rec["metric"]
    assert rec["unit"] == "geomean train samples/sec" and rec["value"] > 0
    assert set(rec["workloads"]) == set(_SUITE_WORKLOADS)
    for name, w in rec["workloads"].items():
        assert w["train_samples_per_sec"] > 0, (name, w)
        assert w["infer_samples_per_sec"] > 0, (name, w)
        # the zero-recompile invariant, counter-verified over the timed
        # region (executor.jit_compile + executor.fused_plan_compile)
        assert w["steady_compiles"] == 0, (name, w)
        assert w["train_outputs_finite"] is True, (name, w)
        assert w["gflops_per_sample_fwd"] > 0, (name, w)
        assert w["window_k"] >= 2 and w["dispatch_depth"] >= 2, (name, w)
        assert w["dtype"] in ("float32", "bfloat16"), (name, w)
    # device-side attribution (ISSUE 18): the suite record is stamped
    # with its layout + recipe, and the flagship resnet-50 leg embeds the
    # per-kernel device-time top-10 ("where did the step time go")
    assert rec["layout"] in ("NCHW", "NHWC"), rec
    assert rec["recipe"] in ("f32", "bf16_master"), rec
    kernels = rec["workloads"]["resnet-50"]["kernels"]
    assert 0 < len(kernels) <= 10, kernels
    for row in kernels:
        assert row["name"] and row["device_us"] > 0 and row["calls"] >= 1
        assert 0 <= row["pct"] <= 1
    dcgan = rec["workloads"]["dcgan"]
    assert dcgan["legacy_train_samples_per_sec"] > 0
    speedup = dcgan["fused_speedup"]
    if speedup < 1.0:
        # shared-host noise guard: one dcgan-only re-measure (with the
        # default deeper timed region) before declaring the fused window
        # lost to the imperative loop
        rec2 = _run_bench(_bench_env(BENCH_MODE="suite",
                                     BENCH_SUITE_WORKLOADS="dcgan"))
        speedup = max(speedup, rec2["workloads"]["dcgan"]["fused_speedup"])
    assert speedup >= 1.0, (
        f"fused G/D window at {speedup}x of the legacy loop — "
        f"the whole-zoo fast path regressed for dcgan")


def test_bench_score_sweep_smoke():
    """BENCH_MODE=score: the benchmark_score.py-parity sweep as one
    gateable record — a subset here (the full 14-symbol table is the TPU
    round's run; the registry itself is pinned in
    test_whole_zoo_fastpath.py)."""
    rec = _run_bench(_bench_env(BENCH_MODE="score",
                                BENCH_SCORE_NETS="mlp,lenet",
                                BENCH_ITERS="2", BENCH_SCORE_BATCH="2"))
    assert "zoo_score_sweep" in rec["metric"]
    assert "cpusmoke" in rec["metric"]
    assert rec["unit"] == "geomean images/sec" and rec["value"] > 0
    assert set(rec["networks"]) == {"mlp", "lenet"}
    for name, n in rec["networks"].items():
        assert n["samples_per_sec"] > 0, (name, n)
    assert rec["networks"]["lenet"]["gflops_per_sample_fwd"] > 0


def test_score_symbol_list_is_shared():
    """bench.py's score mode and examples/benchmark_score.py must sweep
    the SAME registry (models.SCORE_SYMBOLS) — two drifting symbol lists
    would make the scoreboard and the example disagree about 'the zoo'."""
    sys.path.insert(0, _ROOT)
    from mxnet_tpu import models

    assert len(models.SCORE_SYMBOLS) >= 14
    for fname in ("bench.py", os.path.join("examples",
                                           "benchmark_score.py")):
        with open(os.path.join(_ROOT, fname)) as f:
            assert "SCORE_SYMBOLS" in f.read(), (
                f"{fname} no longer reads the shared symbol registry")


def test_bench_io_mode_scaling_curve():
    """BENCH_MODE=io: the decode-plane record must carry the full
    worker-scaling curve, the serial baseline, the gated pool_speedup
    ratio and a flowing io.plane.* telemetry snapshot. The pool(>=4) >=
    2x serial pin applies only where parallel decode is physically
    possible (>= 4 host cores); on fewer cores no thread pool can beat
    serial decode, so — exactly like the sharded-serve smoke, whose
    curve slope is also the TPU round's acceptance — this box pins
    structure plus bounded pool overhead instead."""
    knobs = dict(BENCH_MODE="io", BENCH_IO_RECORDS="224",
                 BENCH_IO_WORKERS="1,2,4")
    rec = _run_bench(_bench_env(**knobs))
    assert "io_plane_decode" in rec["metric"]
    assert "cpusmoke" in rec["metric"]
    assert rec["unit"] == "images/sec" and rec["value"] > 0
    assert rec["serial_img_per_sec"] > 0
    assert sorted(rec["scaling"]) == ["1", "2", "4"]
    assert all(v > 0 for v in rec["scaling"].values()), rec["scaling"]
    plane = rec["telemetry"]["io"]["plane"]
    assert plane["batches"] > 0 and plane["records"] > 0
    # absent from the snapshot when never incremented — a clean run
    assert plane.get("worker_crash", 0) == 0
    assert plane.get("worker_stall", 0) == 0
    speedup = rec["pool_speedup"]
    # the bar the ISSUE states, applied where it is measurable; one
    # re-measure before failing (shared-host noise guard)
    floor = 2.0 if os.cpu_count() >= 4 else 0.6
    if speedup < floor:
        speedup = max(speedup, _run_bench(_bench_env(**knobs))["pool_speedup"])
    assert speedup >= floor, (
        f"decode pool at {speedup}x of serial on {os.cpu_count()} cores "
        f"(floor {floor}x) — the parallel plane regressed")


def test_bench_fit_recordio_leg():
    """BENCH_FIT_DATA=recordio: Module.fit trained from a generated
    RecordIO file through the full decode pool + prefetch stack must
    reach >= 70% of the synthetic (in-memory NDArrayIter) fit rate —
    the input plane keeps the chip fed."""
    knobs = dict(BENCH_MODE="fit", BENCH_LAYERS="18", BENCH_BATCH="4",
                 BENCH_ITERS="3", BENCH_WINDOWS="2", BENCH_GUARD="0",
                 BENCH_WARM_START="0", BENCH_KERNELS="0")
    syn = _run_bench(_bench_env(**knobs))
    rec = _run_bench(_bench_env(BENCH_FIT_DATA="recordio", **knobs))
    assert rec["fit_data"] == "recordio"
    assert "recordio" in rec["metric"]
    rate = rec["value"]
    if rate < 0.7 * syn["value"]:
        # shared-host noise guard: one re-measure before declaring the
        # decode plane unable to feed the training loop
        rate = max(rate, _run_bench(
            _bench_env(BENCH_FIT_DATA="recordio", **knobs))["value"])
    assert rate >= 0.7 * syn["value"], (
        f"recordio fit at {rate} img/s vs synthetic {syn['value']} "
        f"img/s — the decode plane starves the training loop")


@pytest.mark.slow
def test_bench_xla_flag_sweep_smoke():
    """BENCH_SWEEP=xla: the compiler-flag sweep must try every candidate
    from BENCH_SWEEP_XLA through MXNET_XLA_FLAGS (a rebuilt module per
    candidate — the flags feed compile options AND the AOT fingerprint),
    record the per-candidate table, and adopt a winner. slow-marked: a
    sweep is an extra fit compile per candidate on top of the headline
    run; the flag-threading itself is unit-pinned in test_executor.py."""
    rec = _run_bench(_bench_env(
        BENCH_MODE="fit", BENCH_LAYERS="18", BENCH_BATCH="4",
        BENCH_ITERS="2", BENCH_WINDOWS="1", BENCH_WARM_START="0",
        BENCH_KERNELS="0", BENCH_SWEEP="xla",
        BENCH_SWEEP_XLA="xla_cpu_enable_fast_math=true"))
    sweep = rec["sweep"]
    assert sweep and sweep[0]["xla_flags"] == "xla_cpu_enable_fast_math=true"
    assert sweep[0]["img_per_sec"] > 0, sweep
    assert "best_xla_flags" in rec, rec
    assert rec["value"] > 0


def test_hlo_audit_fused_window_clean():
    """tools/hlo_audit.py on the fused resnet-18 window program: every
    donated buffer must be aliased in the compiled executable (zero
    un-aliased donations, zero silently dropped marks) and the bf16
    recipe must show no stray f32 upcasts beyond the per-step gradient
    promotions the master-weight design requires."""
    env = _bench_env(MXNET_AOT_CACHE="0")
    out = os.path.join(tempfile.mkdtemp(prefix="hlo_audit_"), "verdict.json")
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "hlo_audit.py"),
         "--layers", "18", "--batch", "2", "--window", "2", "--json", out],
        capture_output=True, text=True, env=env, timeout=900, cwd=_ROOT,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    with open(out) as f:
        verdict = json.load(f)
    assert verdict["ok"] is True, verdict
    assert verdict["unaliased_donations"] == [], verdict
    assert verdict["dropped_donations"] == 0, verdict
    assert verdict["donated_args"] > 0, verdict
    assert verdict["aliased_args"] + verdict["donor_args"] \
        == verdict["donated_args"], verdict
    assert verdict["stray_upcasts"] == {}, verdict


def test_graft_entry_single_chip_compiles():
    """entry() returns a jittable forward; eval_shape validates the trace
    without paying device compile time."""
    import jax

    sys.path.insert(0, _ROOT)
    import __graft_entry__ as g

    fn, (args, auxs) = g.entry()
    out = jax.eval_shape(fn, args, auxs)
    assert tuple(out.shape) == (8, 1000)
