// Header-only C++ binding over the core C ABI.
//
// Reference: cpp-package/include/mxnet-cpp (header-only wrappers generated
// over the C ABI, with RAII handles and operator sugar; examples mlp.cpp /
// lenet.cpp, CI via cpp-package/tests/ci_test.sh). This is the TPU-native
// analogue over mxtpu.h / libmxtpu.so: NDArray, Symbol and Executor RAII
// classes plus imperative op invocation — enough surface for the
// reference-style C++ inference/training clients.
//
// Build: compile against the amalgamated header+library
// (tools/amalgamation.py):
//   g++ -std=c++17 my_app.cc -I<amal_dir> -I<repo>/cpp_package \
//       <amal_dir>/libmxtpu.so -Wl,-rpath,<amal_dir>
#ifndef MXTPU_CPP_HPP_
#define MXTPU_CPP_HPP_

#include <cstdint>
#include <cstdio>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

#include "mxtpu.h"

namespace mxtpu {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

class NDArray {
 public:
  NDArray() : handle_(nullptr), owned_(false) {}
  explicit NDArray(NDArrayHandle h, bool owned = true)
      : handle_(h), owned_(owned) {}
  NDArray(const std::vector<uint32_t>& shape, int dev_type = 1,
          int dev_id = 0, int dtype = 0)
      : owned_(true) {
    Check(MXNDArrayCreateEx(shape.data(), (uint32_t)shape.size(), dev_type,
                            dev_id, 0, dtype, &handle_));
  }
  NDArray(NDArray&& o) noexcept : handle_(o.handle_), owned_(o.owned_) {
    o.handle_ = nullptr;
    o.owned_ = false;
  }
  NDArray& operator=(NDArray&& o) noexcept {
    reset();
    handle_ = o.handle_;
    owned_ = o.owned_;
    o.handle_ = nullptr;
    o.owned_ = false;
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;
  ~NDArray() { reset(); }

  NDArrayHandle handle() const { return handle_; }
  bool valid() const { return handle_ != nullptr; }

  void SyncCopyFromCPU(const float* data, size_t n_elem) {
    Check(MXNDArraySyncCopyFromCPU(handle_, data, n_elem));
  }
  void SyncCopyToCPU(float* data, size_t n_elem) const {
    Check(MXNDArraySyncCopyToCPU(handle_, data, n_elem));
  }
  std::vector<uint32_t> shape() const {
    uint32_t ndim;
    const uint32_t* dims;
    Check(MXNDArrayGetShape(handle_, &ndim, &dims));
    return std::vector<uint32_t>(dims, dims + ndim);
  }
  size_t size() const {
    size_t s = 1;
    for (uint32_t d : shape()) s *= d;
    return s;
  }
  int dtype() const {
    int dt;
    Check(MXNDArrayGetDType(handle_, &dt));
    return dt;
  }
  NDArray Slice(uint32_t begin, uint32_t end) const {
    NDArrayHandle out;
    Check(MXNDArraySlice(handle_, begin, end, &out));
    return NDArray(out);
  }
  NDArray Reshape(const std::vector<int>& dims) const {
    NDArrayHandle out;
    Check(MXNDArrayReshape(handle_, (int)dims.size(),
                           const_cast<int*>(dims.data()), &out));
    return NDArray(out);
  }
  static void Save(const std::string& fname,
                   const std::map<std::string, NDArray*>& arrays) {
    std::vector<NDArrayHandle> handles;
    std::vector<const char*> keys;
    for (auto& kv : arrays) {
      keys.push_back(kv.first.c_str());
      handles.push_back(kv.second->handle());
    }
    Check(MXNDArraySave(fname.c_str(), (uint32_t)handles.size(),
                        handles.data(), keys.data()));
  }
  static std::map<std::string, NDArray> Load(const std::string& fname) {
    uint32_t n, n_names;
    NDArrayHandle* arrs;
    const char** names;
    Check(MXNDArrayLoad(fname.c_str(), &n, &arrs, &n_names, &names));
    std::map<std::string, NDArray> out;
    for (uint32_t i = 0; i < n; ++i) {
      std::string key = (i < n_names) ? names[i] : std::to_string(i);
      out.emplace(key, NDArray(arrs[i]));
    }
    return out;
  }

 private:
  void reset() {
    if (handle_ && owned_) MXNDArrayFree(handle_);
    handle_ = nullptr;
  }
  NDArrayHandle handle_;
  bool owned_;
};

// imperative op invocation (the generated-operator analogue of
// cpp-package's op.h, resolved by name at runtime)
inline std::vector<NDArray> Invoke(
    const std::string& op_name, const std::vector<NDArray*>& inputs,
    const std::map<std::string, std::string>& params = {}) {
  static std::map<std::string, AtomicSymbolCreator> registry = [] {
    std::map<std::string, AtomicSymbolCreator> reg;
    uint32_t n;
    AtomicSymbolCreator* creators;
    Check(MXSymbolListAtomicSymbolCreators(&n, &creators));
    for (uint32_t i = 0; i < n; ++i) {
      const char* name;
      Check(MXSymbolGetAtomicSymbolName(creators[i], &name));
      reg[name] = creators[i];
    }
    return reg;
  }();
  auto it = registry.find(op_name);
  if (it == registry.end())
    throw std::runtime_error("unknown op " + op_name);
  std::vector<NDArrayHandle> ins;
  for (auto* p : inputs) ins.push_back(p->handle());
  std::vector<const char*> keys, vals;
  for (auto& kv : params) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  int n_out = 0;
  NDArrayHandle* outs = nullptr;
  Check(MXImperativeInvoke(it->second, (int)ins.size(), ins.data(), &n_out,
                           &outs, (int)keys.size(), keys.data(),
                           vals.data()));
  std::vector<NDArray> result;
  for (int i = 0; i < n_out; ++i) result.emplace_back(outs[i]);
  return result;
}

class Symbol {
 public:
  Symbol() : handle_(nullptr) {}
  explicit Symbol(SymbolHandle h) : handle_(h) {}
  static Symbol FromJSON(const std::string& json) {
    SymbolHandle h;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }
  static Symbol FromFile(const std::string& fname) {
    SymbolHandle h;
    Check(MXSymbolCreateFromFile(fname.c_str(), &h));
    return Symbol(h);
  }
  Symbol(Symbol&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  Symbol& operator=(Symbol&& o) noexcept {
    if (handle_) MXSymbolFree(handle_);
    handle_ = o.handle_;
    o.handle_ = nullptr;
    return *this;
  }
  Symbol(const Symbol&) = delete;
  Symbol& operator=(const Symbol&) = delete;
  ~Symbol() {
    if (handle_) MXSymbolFree(handle_);
  }

  SymbolHandle handle() const { return handle_; }
  std::string ToJSON() const {
    const char* js;
    Check(MXSymbolSaveToJSON(handle_, &js));
    return js;
  }
  std::vector<std::string> ListArguments() const {
    return list_impl(MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return list_impl(MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return list_impl(MXSymbolListAuxiliaryStates);
  }
  // construction tier (reference Symbol::Variable / operator())
  static Symbol Variable(const std::string& name) {
    SymbolHandle h;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }
  Symbol Copy() const {
    SymbolHandle h;
    Check(MXSymbolCopy(handle_, &h));
    return Symbol(h);
  }

 private:
  template <typename F>
  std::vector<std::string> list_impl(F f) const {
    uint32_t n;
    const char** strs;
    Check(f(handle_, &n, &strs));
    return std::vector<std::string>(strs, strs + n);
  }
  SymbolHandle handle_;
};

namespace detail {
// plumbing shared by the generated op wrappers (mxtpu_ops.hpp)
using ParamMap = std::vector<std::pair<std::string, std::string>>;

inline std::string str(int64_t v) { return std::to_string(v); }
inline std::string str(double v) {
  // std::to_string's fixed 6 decimals would zero small values (eps=1e-10)
  char buf[32];
  snprintf(buf, sizeof buf, "%.17g", v);
  return buf;
}
inline std::string str(bool v) { return v ? "1" : "0"; }

inline AtomicSymbolCreator CreatorByName(const std::string& op) {
  static std::map<std::string, AtomicSymbolCreator> table = [] {
    std::map<std::string, AtomicSymbolCreator> t;
    uint32_t n;
    AtomicSymbolCreator* creators;
    Check(MXSymbolListAtomicSymbolCreators(&n, &creators));
    for (uint32_t i = 0; i < n; ++i) {
      const char* name;
      Check(MXSymbolGetAtomicSymbolName(creators[i], &name));
      t[name] = creators[i];
    }
    return t;
  }();
  auto it = table.find(op);
  if (it == table.end())
    throw std::runtime_error("no such operator: " + op);
  return it->second;
}

inline Symbol MakeAtomic(const std::string& op, const ParamMap& params) {
  std::vector<const char*> keys, vals;
  for (auto& kv : params) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  SymbolHandle h;
  Check(MXSymbolCreateAtomicSymbol(CreatorByName(op),
                                   (uint32_t)params.size(), keys.data(),
                                   vals.data(), &h));
  return Symbol(h);
}

// fixed-arity op: keyword-wire the provided inputs (missing ones become
// auto-created variables named {symbol_name}_{arg}, as in Python)
inline Symbol CreateOp(const std::string& op, const std::string& name,
                       size_t num_args, const char** arg_keys,
                       const Symbol* const* inputs, const ParamMap& params) {
  Symbol s = MakeAtomic(op, params);
  std::vector<const char*> keys;
  std::vector<SymbolHandle> args;
  for (size_t i = 0; i < num_args; ++i) {
    if (inputs[i] != nullptr) {
      keys.push_back(arg_keys[i]);
      args.push_back(inputs[i]->handle());
    }
  }
  Check(MXSymbolCompose(s.handle(), name.c_str(), (uint32_t)args.size(),
                        keys.empty() ? nullptr : keys.data(),
                        args.empty() ? nullptr : args.data()));
  return s;
}

// variadic op (Concat, add_n, ...): positional inputs
inline Symbol CreateOpN(const std::string& op, const std::string& name,
                        const std::vector<const Symbol*>& inputs,
                        const ParamMap& params) {
  Symbol s = MakeAtomic(op, params);
  std::vector<SymbolHandle> args;
  for (auto* in : inputs) args.push_back(in->handle());
  Check(MXSymbolCompose(s.handle(), name.c_str(), (uint32_t)args.size(),
                        nullptr, args.empty() ? nullptr : args.data()));
  return s;
}
}  // namespace detail

class Executor {
 public:
  // in_args parallel to symbol.ListArguments(); aux parallel to
  // ListAuxiliaryStates(); grad_req 0 everywhere = inference
  Executor(const Symbol& symbol, int dev_type, int dev_id,
           const std::vector<NDArray*>& in_args,
           const std::vector<NDArray*>& aux_states = {},
           const std::vector<uint32_t>& grad_req = {}) {
    std::vector<NDArrayHandle> args, auxs;
    for (auto* a : in_args) args.push_back(a->handle());
    for (auto* a : aux_states) auxs.push_back(a->handle());
    std::vector<uint32_t> req =
        grad_req.empty() ? std::vector<uint32_t>(args.size(), 0) : grad_req;
    Check(MXExecutorBind(symbol.handle(), dev_type, dev_id,
                         (uint32_t)args.size(), args.data(), nullptr,
                         req.data(), (uint32_t)auxs.size(),
                         auxs.empty() ? nullptr : auxs.data(), &handle_));
  }
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;
  ~Executor() {
    if (handle_) MXExecutorFree(handle_);
  }

  void Forward(bool is_train = false) {
    Check(MXExecutorForward(handle_, is_train ? 1 : 0));
  }
  void Backward(const std::vector<NDArray*>& head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (auto* g : head_grads) hg.push_back(g->handle());
    Check(MXExecutorBackward(handle_, (uint32_t)hg.size(),
                             hg.empty() ? nullptr : hg.data()));
  }
  std::vector<NDArray> Outputs() const {
    uint32_t n;
    NDArrayHandle* outs;
    Check(MXExecutorOutputs(handle_, &n, &outs));
    std::vector<NDArray> result;
    for (uint32_t i = 0; i < n; ++i) result.emplace_back(outs[i]);
    return result;
  }

 private:
  ExecutorHandle handle_ = nullptr;
};

}  // namespace cpp
}  // namespace mxtpu

#endif  // MXTPU_CPP_HPP_
