// LeNet inference through the C++ binding (the cpp-package lenet example
// analogue): load a symbol JSON + .params checkpoint, bind an executor,
// run a forward pass and print the probabilities.
//
// Usage: lenet_inference <symbol.json> <checkpoint.params>
#include <cstdio>
#include <cstring>

#include "mxtpu_cpp.hpp"

using mxtpu::cpp::Executor;
using mxtpu::cpp::Invoke;
using mxtpu::cpp::NDArray;
using mxtpu::cpp::Symbol;

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s symbol.json params\n", argv[0]);
    return 2;
  }
  try {
    Symbol net = Symbol::FromFile(argv[1]);
    auto loaded = NDArray::Load(argv[2]);

    auto arg_names = net.ListArguments();
    std::vector<NDArray> storage;
    storage.reserve(arg_names.size());
    std::vector<NDArray*> args;
    NDArray* data = nullptr;
    for (auto& name : arg_names) {
      auto it = loaded.find("arg:" + name);
      if (it != loaded.end()) {
        storage.emplace_back(std::move(it->second));
      } else if (name == "data") {
        storage.emplace_back(std::vector<uint32_t>{2, 1, 28, 28});
        data = &storage.back();
      } else {  // label etc.
        storage.emplace_back(std::vector<uint32_t>{2});
      }
      args.push_back(&storage.back());
    }

    std::vector<float> input(2 * 28 * 28);
    for (size_t i = 0; i < input.size(); ++i)
      input[i] = float(i % 29) / 29.0f;
    data->SyncCopyFromCPU(input.data(), input.size());

    Executor exe(net, /*dev_type=*/1, /*dev_id=*/0, args);
    exe.Forward(false);
    auto outs = exe.Outputs();
    std::vector<float> probs(outs[0].size());
    outs[0].SyncCopyToCPU(probs.data(), probs.size());
    for (float p : probs) std::printf("%.6f\n", p);

    // exercise the imperative surface too: argmax over the probabilities
    auto cls = Invoke("argmax", {&outs[0]}, {{"axis", "1"}});
    std::vector<float> idx(cls[0].size());
    cls[0].SyncCopyToCPU(idx.data(), idx.size());
    std::fprintf(stderr, "argmax: %d %d\n", (int)idx[0], (int)idx[1]);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAIL: %s\n", e.what());
    return 1;
  }
  return 0;
}
