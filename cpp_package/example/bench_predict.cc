// Deployment-path benchmark client: time MXPred* inference over the
// amalgamated library (the reference's amalgamation exists precisely for
// this deployment story). Prints one line per run:
//   C <batch> <img_per_sec>
// Usage: bench_predict <symbol.json> <params> <batch> <iters> [dev_type]
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "mxtpu.h"

static std::string slurp(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    fprintf(stderr, "cannot open %s\n", path);
    exit(1);
  }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string s(n, '\0');
  if (fread(&s[0], 1, n, f) != size_t(n)) exit(1);
  fclose(f);
  return s;
}

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s sym.json params batch iters [dev_type]\n",
            argv[0]);
    return 2;
  }
  std::string sym = slurp(argv[1]);
  std::string params = slurp(argv[2]);
  int batch = atoi(argv[3]);
  int iters = atoi(argv[4]);
  int dev_type = argc > 5 ? atoi(argv[5]) : 2;

  const char* keys[] = {"data"};
  uint32_t indptr[] = {0, 4};
  uint32_t dims[] = {uint32_t(batch), 3, 224, 224};
  PredictorHandle pred = nullptr;
  if (MXPredCreate(sym.c_str(), params.data(), int(params.size()), dev_type,
                   0, 1, keys, indptr, dims, &pred) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return 1;
  }
  size_t in_elems = size_t(batch) * 3 * 224 * 224;
  std::vector<float> input(in_elems);
  for (size_t i = 0; i < in_elems; ++i) input[i] = float(i % 255) / 255.f;
  std::vector<float> output(size_t(batch) * 1000);

  auto once = [&]() {
    if (MXPredSetInput(pred, "data", input.data(), uint32_t(in_elems)) ||
        MXPredForward(pred) ||
        MXPredGetOutput(pred, 0, output.data(), uint32_t(output.size()))) {
      fprintf(stderr, "predict: %s\n", MXGetLastError());
      exit(1);
    }
  };
  for (int i = 0; i < 3; ++i) once();  // warmup/compile
  auto tic = std::chrono::steady_clock::now();
  for (int i = 0; i < iters; ++i) once();
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - tic).count();
  printf("C %d %.2f\n", batch, batch * iters / dt);
  MXPredFree(pred);
  return 0;
}
