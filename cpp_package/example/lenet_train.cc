// Build LeNet FROM OPS (no JSON load) and run training steps — the
// reference cpp-package lenet.cpp pattern over the C ABI construction
// tier: generated op wrappers (mxtpu_ops.hpp) -> MXSymbolCreateAtomic-
// Symbol/Compose, MXExecutorSimpleBind allocation, and a KVStore whose
// MXKVStoreSetUpdater callback applies SGD through MXImperativeInvoke.
//
// Prints "loss0 loss1" (cross-entropy before/after one update) on
// stdout; the python test replicates the exact flow and compares.
#include <cmath>
#include <cstdio>
#include <string>
#include <vector>

#include "mxtpu_cpp.hpp"
#include "mxtpu_ops.hpp"

using mxtpu::cpp::Check;
using mxtpu::cpp::NDArray;
using mxtpu::cpp::Symbol;
namespace op = mxtpu::cpp::op;

static const float kLR = 0.01f;

// SGD through the imperative registry: local -= lr * recv (in place)
static void sgd_updater(int key, NDArrayHandle recv, NDArrayHandle local,
                        void* /*unused*/) {
  NDArrayHandle inputs[2] = {local, recv};
  int num_outputs = 1;
  NDArrayHandle outs_storage[1] = {local};
  NDArrayHandle* outputs = outs_storage;
  const char* keys[] = {"lr", "wd"};
  char lr_s[32];
  snprintf(lr_s, sizeof lr_s, "%f", kLR);
  const char* vals[] = {lr_s, "0.0"};
  Check(MXImperativeInvoke(
      mxtpu::cpp::detail::CreatorByName("sgd_update"), 2, inputs,
      &num_outputs, &outputs, 2, keys, vals));
}

int main() {
  const uint32_t B = 8, CLS = 10;

  // ---- LeNet from ops (models/lenet structure) ----
  Symbol data = Symbol::Variable("data");
  Symbol c1 = op::Convolution("conv1", data, "(5, 5)", 20);
  Symbol a1 = op::Activation("act1", c1, "tanh");
  Symbol p1 = op::Pooling("pool1", a1, /*cudnn_off=*/false,
                          /*global_pool=*/false, "(2, 2)", "", "max",
                          "valid", "(2, 2)");
  Symbol c2 = op::Convolution("conv2", p1, "(5, 5)", 50);
  Symbol a2 = op::Activation("act2", c2, "tanh");
  Symbol p2 = op::Pooling("pool2", a2, /*cudnn_off=*/false,
                          /*global_pool=*/false, "(2, 2)", "", "max",
                          "valid", "(2, 2)");
  Symbol fl = op::Flatten("flat", p2);
  Symbol f1 = op::FullyConnected("fc1", fl, 500);
  Symbol a3 = op::Activation("act3", f1, "tanh");
  Symbol f2 = op::FullyConnected("fc2", a3, CLS);
  Symbol net = op::SoftmaxOutput("softmax", f2);

  // ---- SimpleBind: infer + allocate everything ----
  const char* shape_names[] = {"data", "softmax_label"};
  uint32_t shape_data[] = {B, 1, 28, 28, B};
  uint32_t shape_idx[] = {0, 4, 5};
  const char* req_types[] = {"write"};
  int shared_buffer_len = -1;
  uint32_t num_in_args = 0, num_aux = 0;
  NDArrayHandle *in_args = nullptr, *arg_grads = nullptr, *aux = nullptr;
  ExecutorHandle exec = nullptr;
  Check(MXExecutorSimpleBind(
      net.handle(), 1 /*cpu*/, 0, 0, nullptr, nullptr, nullptr,
      0, nullptr, req_types, 2, shape_names, shape_data, shape_idx,
      0, nullptr, nullptr, 0, nullptr, nullptr, 0, nullptr,
      &shared_buffer_len, nullptr, nullptr, nullptr, nullptr,
      &num_in_args, &in_args, &arg_grads, &num_aux, &aux, nullptr, &exec));

  std::vector<std::string> arg_names = net.ListArguments();
  if (arg_names.size() != num_in_args) {
    fprintf(stderr, "arg count mismatch\n");
    return 1;
  }

  // ---- deterministic params + batch (mirrored by the python test) ----
  std::vector<float> buf;
  for (uint32_t i = 0; i < num_in_args; ++i) {
    NDArray a(in_args[i], false);
    buf.resize(a.size());
    if (arg_names[i] == "data") {
      for (size_t j = 0; j < buf.size(); ++j) buf[j] = (j % 29) / 29.0f;
    } else if (arg_names[i] == "softmax_label") {
      for (size_t j = 0; j < buf.size(); ++j) buf[j] = (float)(j % CLS);
    } else {
      for (size_t j = 0; j < buf.size(); ++j)
        buf[j] = 0.05f * std::sin((double)(j % 1997));
    }
    a.SyncCopyFromCPU(buf.data(), buf.size());
  }

  // ---- kvstore with the C updater ----
  KVStoreHandle kv;
  Check(MXKVStoreCreate("local", &kv));
  Check(MXKVStoreSetUpdater(kv, sgd_updater, nullptr));
  std::vector<int> pkeys;
  for (uint32_t i = 0; i < num_in_args; ++i) {
    if (arg_names[i] == "data" || arg_names[i] == "softmax_label") continue;
    int k = (int)i;
    Check(MXKVStoreInit(kv, 1, &k, &in_args[i]));
    pkeys.push_back(k);
  }

  auto loss = [&]() -> double {
    uint32_t n_out;
    NDArrayHandle* outs;
    Check(MXExecutorOutputs(exec, &n_out, &outs));
    NDArray probs(outs[0]);
    std::vector<float> p(probs.size());
    probs.SyncCopyToCPU(p.data(), p.size());
    double total = 0;
    for (uint32_t b = 0; b < B; ++b)
      total += -std::log((double)p[b * CLS + (b % CLS)] + 1e-12);
    return total / B;
  };

  Check(MXExecutorForward(exec, 1));
  double loss0 = loss();
  Check(MXExecutorBackward(exec, 0, nullptr));
  for (int k : pkeys) {
    Check(MXKVStorePush(kv, 1, &k, &arg_grads[k], 0));
    Check(MXKVStorePull(kv, 1, &k, &in_args[k], 0));
  }
  Check(MXExecutorForward(exec, 1));
  double loss1 = loss();
  printf("%.6f %.6f\n", loss0, loss1);

  MXKVStoreFree(kv);
  MXExecutorFree(exec);
  return 0;
}
