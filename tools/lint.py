#!/usr/bin/env python
"""graftlint CLI — run the framework-invariant static-analysis suite.

Usage:
    python tools/lint.py                     # lint the tree, text report
    python tools/lint.py --format=json       # machine-readable report
    python tools/lint.py --check host-sync   # one checker only
    python tools/lint.py --only=host-sync,lock-discipline  # a subset
    python tools/lint.py --callgraph DecodePool.next_result  # debug:
                                             # resolved callees/callers
    python tools/lint.py --write-baseline    # grandfather current findings
    python tools/lint.py path/to/file.py ... # lint specific files

Exit status: 0 when the tree is clean (no findings beyond the baseline),
1 when new findings exist, 2 on usage errors. ``--write-baseline``
regenerates ``tools/lint_baseline.json`` deterministically (sorted,
path-relative, line-number free) so its diffs are reviewable.

The analysis package is loaded standalone (it is stdlib-only and uses
relative imports exclusively), so linting works without importing the
framework or jax.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BASELINE = os.path.join(_ROOT, "tools", "lint_baseline.json")


def _load_analysis():
    # import the self-contained package as top-level `analysis` — pulling
    # it in as mxnet_tpu.analysis would execute mxnet_tpu/__init__ and
    # drag jax into a pure static-analysis CLI
    sys.path.insert(0, os.path.join(_ROOT, "mxnet_tpu"))
    try:
        import analysis
    finally:
        sys.path.pop(0)
    return analysis


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="lint.py", description="graftlint static-analysis suite")
    p.add_argument("paths", nargs="*",
                   help="files to lint (default: the framework scope)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--check", action="append", dest="checks",
                   metavar="NAME", help="run only this checker "
                   "(repeatable); see --list")
    p.add_argument("--only", metavar="NAME[,NAME...]",
                   help="run only these checkers (comma-separated "
                   "spelling of --check, for fast iteration)")
    p.add_argument("--callgraph", metavar="QUALNAME",
                   help="debug mode: print the resolved callees/callers/"
                   "unresolved calls for every function whose qualified "
                   "name matches (suffix match, e.g. "
                   "'DecodePool.next_result'), plus graph-wide stats; "
                   "no linting happens")
    p.add_argument("--list", action="store_true",
                   help="list checkers and exit")
    p.add_argument("--write-baseline", action="store_true",
                   help="record current findings as the baseline")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings as new")
    p.add_argument("--root", default=_ROOT, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    analysis = _load_analysis()

    if args.list:
        for c in analysis.all_checkers():
            print(f"{c.name:20s} {c.doc}")
        return 0

    if args.only:
        args.checks = (args.checks or []) + [
            c.strip() for c in args.only.split(",") if c.strip()]
    known = set(analysis.checker_names())
    for c in args.checks or ():
        if c not in known:
            p.error(f"unknown checker {c!r} (have: {sorted(known)})")

    if args.callgraph:
        ctx = analysis.build_context(
            args.root,
            [os.path.abspath(f) for f in args.paths] if args.paths
            else None)
        graph = ctx.callgraph()
        hits = graph.find(args.callgraph)
        if not hits:
            print(f"no function matches {args.callgraph!r}",
                  file=sys.stderr)
            return 2
        for node_id in hits:
            print(graph.describe(node_id))
            print()
        s = graph.stats()
        print(f"graph: {s['functions']} functions, "
              f"{s['edges']} resolved call edges, "
              f"{s['unresolved_calls']} unresolved calls")
        return 0

    files = None
    if args.paths:
        if args.write_baseline:
            p.error("--write-baseline regenerates the TREE-wide baseline "
                    "and cannot be combined with explicit paths (it would "
                    "silently drop every other file's entries)")
        files = [os.path.abspath(f) for f in args.paths]
    baseline = None if (args.no_baseline or args.write_baseline) \
        else analysis.load_baseline(_BASELINE)
    result = analysis.run_suite(args.root, files=files, checks=args.checks,
                                baseline=baseline)

    if args.write_baseline:
        analysis.write_baseline(result.findings, _BASELINE)
        print(f"baseline written: {_BASELINE} "
              f"({len(result.findings)} findings)")
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [dict(f.as_dict(), line=f.line)
                         for f in result.findings],
            "baselined": len(result.baselined),
            "suppressed": len(result.suppressed),
            "stale_baseline": result.stale_baseline,
        }, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
        print(f"graftlint: {len(result.findings)} new finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{len(result.suppressed)} pragma-suppressed")
        if result.stale_baseline:
            print(f"note: {len(result.stale_baseline)} baseline entr"
                  f"{'y is' if len(result.stale_baseline) == 1 else 'ies are'}"
                  " no longer hit — shrink the baseline "
                  "(--write-baseline)")
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
