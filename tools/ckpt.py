#!/usr/bin/env python
"""Checkpoint CLI — inspect, verify, and reshard elastic v2 checkpoints.

Usage:
    python tools/ckpt.py inspect <dir|commit>            # manifest summary
    python tools/ckpt.py verify  <dir|commit>            # digest + coverage
    python tools/ckpt.py reshard <dir|commit> --out DIR [--mesh SPEC]

`inspect` is stdlib-only (reads manifest.json directly). `verify` and
`reshard` import the framework (JAX_PLATFORMS defaults to cpu) to reuse
the loader's digest/coverage checks and the elastic reassembly path;
`reshard` rewrites any source shard layout as a single-shard v2 commit
stamped for --mesh, so a checkpoint from one topology can be staged for
another offline, without a training process.

Exit status: 0 clean, 1 corruption / no loadable checkpoint, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _die(msg, code=1):
    print(f"ckpt: {msg}", file=sys.stderr)
    sys.exit(code)


def _is_commit(path):
    return os.path.exists(os.path.join(path, "manifest.json"))


def _commits(root):
    try:
        names = sorted(os.listdir(root), reverse=True)
    except OSError as e:
        _die(f"cannot list {root}: {e}")
    return [os.path.join(root, n) for n in names
            if n.startswith("ckpt-") and
            os.path.isdir(os.path.join(root, n))]


def _newest_commit(path):
    if _is_commit(path):
        return path
    commits = [c for c in _commits(path) if _is_commit(c)]
    if not commits:
        _die(f"no committed checkpoint under {path}")
    return commits[0]


def _fmt_shape(shape):
    return "x".join(str(s) for s in shape) if shape else "scalar"


def cmd_inspect(args):
    path = _newest_commit(args.path)
    with open(os.path.join(path, "manifest.json")) as f:
        m = json.load(f)
    print(f"commit:    {os.path.basename(path)}")
    print(f"format:    v{m.get('format')}")
    print(f"resume at: epoch {m.get('next_epoch')} "
          f"batch {m.get('next_batch')}")
    mesh = m.get("mesh")
    if mesh:
        print(f"mesh:      {mesh.get('spec')} "
              f"({len(mesh.get('devices') or [])} devices, "
              f"{mesh.get('processes')} process(es), "
              f"{mesh.get('platform')})")
    opt = m.get("optimizer") or {}
    if opt:
        print(f"optimizer: num_update={opt.get('num_update')}")
    if m.get("stage_slices"):
        stages = {v["stage"] for v in m["stage_slices"].values()}
        print(f"pipeline:  {len(stages)} packed stage(s), "
              f"{len(m['stage_slices'])} row slice(s)")
    files = m.get("files") or {}
    total = sum(v.get("bytes", 0) for v in files.values())
    print(f"files:     {len(files)} ({total} bytes)")
    for name in sorted(files):
        print(f"  {name:32s} {files[name].get('bytes', 0):>12d} bytes")
    params = m.get("params")
    if params:
        shards = m.get("shards") or {}
        per_param = {}
        for v in shards.values():
            if v.get("domain") == "param":
                per_param[v["name"]] = per_param.get(v["name"], 0) + 1
        print(f"params:    {len(params)}")
        for name in sorted(params):
            p = params[name]
            spec = p.get("spec") or "replicated"
            print(f"  {name:28s} {p['kind']:3s} "
                  f"{_fmt_shape(p.get('shape')):>12s} {p.get('dtype'):>9s} "
                  f"{per_param.get(name, 0):>3d} piece(s)  {spec}")
        opt_names = [n for n, t in (m.get('opt_states') or {}).items()
                     if t is not None]
        print(f"opt state: {len(opt_names)} parameter(s) with saved "
              f"slots")
    return 0


def cmd_verify(args):
    from mxnet_tpu import checkpoint as ckpt

    targets = [args.path] if _is_commit(args.path) else _commits(args.path)
    if not targets:
        _die(f"no commit directories under {args.path}")
    bad = 0
    for path in targets:
        name = os.path.basename(path)
        try:
            m = ckpt.verify_dir(path)
            print(f"OK       {name} (v{m['format']}, resume at epoch "
                  f"{m['next_epoch']} batch {m['next_batch']})")
        except ckpt.CheckpointCorrupt as e:
            bad += 1
            print(f"CORRUPT  {name}: {e}")
    return 1 if bad else 0


def cmd_reshard(args):
    if args.mesh:
        # validate the grammar before paying for the load
        from mxnet_tpu.parallel.mesh import parse_mesh_spec
        try:
            parse_mesh_spec(args.mesh, devices=None)
        except Exception as e:
            _die(f"bad --mesh {args.mesh!r}: {e}", 2)
    from mxnet_tpu import checkpoint as ckpt

    if _is_commit(args.path):
        ckpt.verify_dir(args.path)
        loaded = ckpt._load_one(args.path)
    else:
        loaded = ckpt.load_latest(args.path)
        if loaded is None:
            _die(f"no loadable checkpoint under {args.path}")
    out = ckpt.consolidate(loaded, args.out, mesh_spec=args.mesh)
    m = ckpt.verify_dir(out)
    print(f"resharded {os.path.basename(loaded.path)} -> {out} "
          f"(single shard, {len(m['files'])} files"
          f"{', mesh ' + args.mesh if args.mesh else ''})")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="ckpt.py", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("inspect", cmd_inspect), ("verify", cmd_verify),
                     ("reshard", cmd_reshard)):
        p = sub.add_parser(name)
        p.add_argument("path", help="checkpoint root or commit directory")
        p.set_defaults(fn=fn)
        if name == "reshard":
            p.add_argument("--out", required=True,
                           help="output commit directory")
            p.add_argument("--mesh", default=None,
                           help="mesh spec to stamp (e.g. dp4,pp2)")
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        sys.exit(0)  # e.g. `inspect | head`
