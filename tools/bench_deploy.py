#!/usr/bin/env python
"""Deployment-path benchmark: C client (amalgamated libmxtpu.so, MXPred*
ABI) vs the in-process Python Predictor, ResNet-50 folded, bs1 and bs32.

The reference's amalgamation exists for exactly this deployment story, so
the C path must not tax it: the expectation is C within ~10% of Python
(both run the same folded XLA program; the delta is marshalling —
MXPredSetInput/GetOutput cross the embedded-CPython boundary with raw
float buffers).

Usage: python tools/bench_deploy.py [--dev-type 2] [--iters-bs1 100]
Prints one line per (path, batch) plus a summary ratio.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import sysconfig
import tempfile
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dev-type", type=int, default=2,
                    help="1=cpu 2=accelerator (TPU)")
    ap.add_argument("--iters-bs1", type=int, default=100)
    ap.add_argument("--iters-bs32", type=int, default=20)
    ap.add_argument("--amal-dir", default=None,
                    help="reuse an existing amalgamation build dir")
    args = ap.parse_args()

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import models

    work = tempfile.mkdtemp(prefix="mxtpu_deploy_")
    prefix = os.path.join(work, "resnet50")

    sym = models.resnet(num_classes=1000, num_layers=50,
                        image_shape="3,224,224")
    # random params straight from shape inference — binding an executor
    # just to initialize would compile the whole graph on the host backend
    arg_shapes, _, aux_shapes = sym.infer_shape(
        data=(1, 3, 224, 224), softmax_label=(1,))
    rng = np.random.RandomState(0)
    arg_params, aux_params = {}, {}
    for n, s in zip(sym.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        fan_in = int(np.prod(s[1:])) if len(s) > 1 else int(s[0])
        arg_params[n] = mx.nd.array(
            (rng.randn(*s) * np.sqrt(2.0 / max(fan_in, 1)))
            .astype(np.float32))
    for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
        aux_params[n] = (mx.nd.ones(s) if "var" in n or "gamma" in n
                         else mx.nd.zeros(s))
    mx.model.save_checkpoint(prefix, 0, sym, arg_params, aux_params)
    sym_file, params_file = f"{prefix}-symbol.json", f"{prefix}-0000.params"

    # ---- python predictor ----
    from mxnet_tpu.predictor import Predictor

    results = {}
    for batch, iters in ((1, args.iters_bs1), (32, args.iters_bs32)):
        pred = Predictor(
            open(sym_file).read(), params_file,
            {"data": (batch, 3, 224, 224)},
            dev_type="gpu" if args.dev_type == 2 else "cpu")
        x = (np.arange(batch * 3 * 224 * 224, dtype=np.float32)
             % 255) / 255.0
        x = x.reshape(batch, 3, 224, 224)

        def once():
            pred.set_input("data", x)
            pred.forward()
            return pred.get_output(0)

        for _ in range(3):
            np.asarray(once())
        tic = time.time()
        for _ in range(iters):
            out = once()
        np.asarray(out)
        rate = batch * iters / (time.time() - tic)
        results[("py", batch)] = rate
        print(f"PY {batch} {rate:.2f}", flush=True)

    # ---- C client over the amalgamated .so ----
    amal = args.amal_dir
    if not amal:
        amal = os.path.join(work, "amal")
        r = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools", "amalgamation.py"),
             "--out-dir", amal], capture_output=True, text=True)
        if r.returncode != 0:
            sys.exit(f"amalgamation failed:\n{r.stderr[-2000:]}")
    exe = os.path.join(work, "bench_predict")
    libdir = sysconfig.get_config_var("LIBDIR")
    r = subprocess.run(
        ["g++", "-std=c++17", "-O2",
         os.path.join(_ROOT, "cpp_package", "example", "bench_predict.cc"),
         "-o", exe, f"-I{amal}", os.path.join(amal, "libmxtpu.so"),
         f"-Wl,-rpath,{amal}", f"-Wl,-rpath,{libdir}"],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.exit(f"C build failed:\n{r.stderr[-2000:]}")
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    for batch, iters in ((1, args.iters_bs1), (32, args.iters_bs32)):
        r = subprocess.run(
            [exe, sym_file, params_file, str(batch), str(iters),
             str(args.dev_type)],
            capture_output=True, text=True, env=env, timeout=1200)
        if r.returncode != 0:
            sys.exit(f"C bench failed:\n{r.stderr[-2000:]}")
        line = r.stdout.strip().splitlines()[-1]
        rate = float(line.split()[-1])
        results[("c", batch)] = rate
        print(line, flush=True)

    for batch in (1, 32):
        ratio = results[("c", batch)] / results[("py", batch)]
        print(f"SUMMARY bs{batch}: C/{'PY'} = {ratio:.3f} "
              f"(C {results[('c', batch)]:.1f} vs "
              f"PY {results[('py', batch)]:.1f} img/s)")


if __name__ == "__main__":
    main()
