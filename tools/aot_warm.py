#!/usr/bin/env python
"""Pre-populate the AOT executable cache for a model-zoo symbol + shapes.

Deployments warm the cache OUT OF BAND: run this once per (model, shape
set, backend) on the target host, and every later process that binds the
same signature starts with ``executor.jit_compile == 0`` — the forward,
train-step and (with ``--step``) fused train-update executables
deserialize from ``MXNET_AOT_CACHE_DIR`` instead of recompiling. See
``mxnet_tpu/aot.py`` and docs/architecture.md (AOT dispatch layer).

The cache is enabled for the run regardless of the ambient
``MXNET_AOT_CACHE`` value (populating it is the point); ``--cache-dir``
overrides ``MXNET_AOT_CACHE_DIR``.

Usage:
    python tools/aot_warm.py resnet --data-shape 128,3,224,224 \
        --model-arg num_layers=50 --dtype bfloat16
    python tools/aot_warm.py mlp --data-shape 32,784 --eval-only
    python tools/aot_warm.py lstm-bucketed ...   # not supported; use
        BucketingModule.compile(buckets=...) from python for bucketed models

Multiple ``--data-shape`` values warm one signature per shape (e.g. the
serving batch sizes). ``--step`` additionally runs one real optimizer step
per shape so the donated fused train program (the steady-state training
executable) lands in the cache too; ``--window K`` does the same for a
K-step training window.
"""

import argparse
import os
import sys

# runnable from a checkout without an installed package
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _parse_shape(text):
    try:
        return tuple(int(x) for x in text.split(",") if x != "")
    except ValueError:
        raise argparse.ArgumentTypeError(f"bad shape {text!r}")


def _parse_model_arg(text):
    key, sep, val = text.partition("=")
    if not sep:
        raise argparse.ArgumentTypeError(f"--model-arg wants key=value, got {text!r}")
    for cast in (int, float):
        try:
            return key, cast(val)
        except ValueError:
            pass
    return key, val


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model", help="model-zoo builder name (mxnet_tpu.models.<name>)")
    ap.add_argument("--data-shape", type=_parse_shape, action="append",
                    required=True, metavar="N,C,H,W",
                    help="full data shape incl. batch; repeatable")
    ap.add_argument("--label-name", default="softmax_label")
    ap.add_argument("--no-label", action="store_true",
                    help="symbol takes no label input")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--model-arg", type=_parse_model_arg, action="append",
                    default=[], metavar="KEY=VALUE",
                    help="forwarded to the model builder; repeatable")
    ap.add_argument("--eval-only", action="store_true",
                    help="warm the inference forward program only")
    ap.add_argument("--step", action="store_true",
                    help="also run one real optimizer step per shape so the "
                         "donated fused train executable is cached")
    ap.add_argument("--window", type=int, default=0, metavar="K",
                    help="with --step, also run a K-step training window in "
                         "both variants — repeat-batch (bench.py train "
                         "mode) and stacked-batches (Module.fit's "
                         "MXNET_TRAIN_WINDOW loop) — caching both window "
                         "executables")
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--cache-dir", default=None,
                    help="override MXNET_AOT_CACHE_DIR")
    args = ap.parse_args(argv)

    os.environ["MXNET_AOT_CACHE"] = "1"
    if args.cache_dir:
        os.environ["MXNET_AOT_CACHE_DIR"] = args.cache_dir

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import aot, models

    builder = getattr(models, args.model, None)
    if builder is None or not callable(builder):
        print(f"aot_warm: unknown model {args.model!r} "
              f"(see mxnet_tpu.models)", file=sys.stderr)
        return 2
    sym = builder(**dict(args.model_arg))

    on_tpu = mx.context.num_gpus() > 0
    ctx = mx.gpu() if on_tpu else mx.cpu()
    warmed = []
    for dshape in args.data_shape:
        label_names = () if args.no_label else (args.label_name,)
        mod = mx.mod.Module(sym, context=ctx, label_names=label_names)
        label_shapes = (None if args.no_label
                        else [mx.io.DataDesc(args.label_name, (dshape[0],))])
        mod.bind(
            data_shapes=[mx.io.DataDesc("data", dshape, args.dtype)],
            label_shapes=label_shapes,
            for_training=not args.eval_only,
        )
        mod.init_params(initializer=mx.init.Xavier())
        kinds = mod.compile()
        if args.step and not args.eval_only:
            mod.init_optimizer(optimizer=args.optimizer,
                               optimizer_params={"learning_rate": args.lr})
            rng = np.random.RandomState(0)
            batch = mx.io.DataBatch(
                data=[mx.nd.array(
                    rng.uniform(-1, 1, dshape).astype(np.float32),
                    dtype=args.dtype)],
                label=None if args.no_label else [mx.nd.array(
                    rng.randint(0, 2, (dshape[0],)).astype(np.float32))],
            )
            k = max(1, args.window)
            if k > 1:
                # both window program variants: repeat-batch (bench.py's
                # train mode, train_window(batch, K)) AND stacked-batches
                # (what Module.fit's MXNET_TRAIN_WINDOW loop dispatches —
                # its data_stacks give the plan a different signature).
                # publish_grads=False matches the steady-state loops (fit
                # pipeline + bench): the publish flag is part of the plan
                # key AND the cache digest, so warming the publishing
                # variant would leave the real training loop compiling
                mod.train_window(batch, k, publish_grads=False)
                mod.train_window(None, batches=[batch] * k,
                                 publish_grads=False)
                kinds = kinds + [f"train_window(k={k})",
                                 f"train_window(k={k},stacked)"]
            else:
                mod.forward_backward(batch)
                mod.update()
                kinds = kinds + ["train_update(k=1)"]
            np.asarray(mod.get_outputs()[0]._data).ravel()[:1]
        warmed.append((dshape, kinds))

    cache = aot.cache_dir()
    n_files = len([f for f in os.listdir(cache)]) if os.path.isdir(cache) else 0
    for dshape, kinds in warmed:
        print(f"warmed {args.model}{list(dshape)}: {', '.join(kinds)}")
    print(f"cache: {cache} ({n_files} executables; "
          f"stores={mx.telemetry.counter('aot.cache_store').value}, "
          f"hits={mx.telemetry.counter('aot.cache_hit').value})")
    if not aot.supports_serialization():
        print("note: this backend cannot serialize executables — programs "
              "were compiled for this process only", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
