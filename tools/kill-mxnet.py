#!/usr/bin/env python
"""Kill stray training processes on this host (reference tools/kill-mxnet.py).

The reference greps for its python trainers and SIGKILLs them after a failed
distributed run; same job here for workers launched by tools/launch.py.

  python tools/kill-mxnet.py               # kill launched mxnet_tpu workers
  python tools/kill-mxnet.py my_train.py   # kill by script name instead
"""

from __future__ import annotations

import os
import signal
import sys


def main():
    needle = sys.argv[1] if len(sys.argv) > 1 else None
    me = os.getpid()
    killed = []
    for pid in filter(str.isdigit, os.listdir("/proc")):
        pid = int(pid)
        if pid == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ").decode(errors="ignore")
            with open(f"/proc/{pid}/environ", "rb") as f:
                env = f.read().decode(errors="ignore")
        except OSError:
            continue
        launched = "MXNET_COORDINATOR=" in env and "MXNET_PROC_ID=" in env
        matches = needle is not None and needle in cmd and "python" in cmd
        if launched or matches:
            try:
                os.kill(pid, signal.SIGKILL)
                killed.append((pid, cmd.strip()[:80]))
            except OSError:
                pass
    for pid, cmd in killed:
        print(f"killed {pid}: {cmd}")
    if not killed:
        print("no matching processes")


if __name__ == "__main__":
    main()
