#!/usr/bin/env python
"""Pack an image folder/list into RecordIO (reference tools/im2rec.py).

Supports list generation (--list) and multiprocess packing with resize/
quality options; output .rec files are readable by the reference's iterators
(byte-compatible dmlc RecordIO framing).
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time
from multiprocessing import Pool

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from mxnet_tpu import recordio

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def list_image(root, recursive=False):
    i = 0
    if recursive:
        cat = {}
        for path, _dirs, files in sorted(os.walk(root)):
            for fname in sorted(files):
                if os.path.splitext(fname)[1].lower() not in _EXTS:
                    continue
                fpath = os.path.join(path, fname)
                if path not in cat:
                    cat[path] = len(cat)
                yield (i, os.path.relpath(fpath, root), cat[path])
                i += 1
    else:
        for fname in sorted(os.listdir(root)):
            if os.path.splitext(fname)[1].lower() in _EXTS:
                yield (i, fname, 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for idx, fname, label in image_list:
            fout.write(f"{idx}\t{label}\t{fname}\n")


def read_list(path_in):
    with open(path_in) as fin:
        for line in fin:
            parts = line.strip().split("\t")
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield (idx, parts[-1], labels)


def _pack_one(args_tuple):
    item, root, resize, quality, color = args_tuple
    idx, fname, labels = item
    import numpy as np

    fullpath = os.path.join(root, fname)
    if quality < 0 and not resize:
        # pass-through: raw file bytes, no decode/re-encode (byte-identical
        # to the native plane's pass-through mode); unreadable entries are
        # skipped like the decode path, never abort the whole pack
        try:
            with open(fullpath, "rb") as f:
                raw = f.read()
        except OSError:
            return idx, None
        label = labels[0] if len(labels) == 1 else np.asarray(labels,
                                                             np.float32)
        return idx, recordio.pack(recordio.IRHeader(0, label, idx, 0), raw)
    import cv2

    img = cv2.imread(fullpath, cv2.IMREAD_COLOR if color else cv2.IMREAD_GRAYSCALE)
    if img is None:
        return idx, None
    if resize:
        h, w = img.shape[:2]
        if h > w:
            newsize = (resize, int(h * resize / w))
        else:
            newsize = (int(w * resize / h), resize)
        img = cv2.resize(img, newsize)
    label = labels[0] if len(labels) == 1 else np.asarray(labels, np.float32)
    header = recordio.IRHeader(0, label, idx, 0)
    return idx, recordio.pack_img(header, img, quality=quality)


def im2rec(prefix, root, args):
    image_list = list(read_list(prefix + ".lst"))
    writer = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    work = [(item, root, args.resize, args.quality, args.color) for item in image_list]
    tic = time.time()
    count = 0
    if args.num_thread > 1:
        with Pool(args.num_thread) as pool:
            for idx, buf in pool.imap(_pack_one, work):
                if buf is None:
                    print(f"imread failed for index {idx}", file=sys.stderr)
                    continue
                writer.write_idx(idx, buf)
                count += 1
    else:
        for w in work:
            idx, buf = _pack_one(w)
            if buf is None:
                continue
            writer.write_idx(idx, buf)
            count += 1
    writer.close()
    print(f"packed {count} images in {time.time() - tic:.1f}s")


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO database"
    )
    parser.add_argument("prefix", help="prefix of .lst/.rec/.idx files")
    parser.add_argument("root", help="image root folder")
    parser.add_argument("--list", action="store_true",
                        help="generate the .lst instead of packing")
    parser.add_argument("--recursive", action="store_true")
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--train-ratio", type=float, default=1.0)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--color", type=int, default=1)
    parser.add_argument("--num-thread", type=int, default=1)
    parser.add_argument("--pass-through", action="store_true",
                        help="pack raw file bytes (no decode/re-encode)")
    parser.add_argument("--native", action="store_true",
                        help="pack through the C++ io plane "
                             "(native/io_plane.cpp mxio_pack_list)")
    args = parser.parse_args()
    if args.pass_through:
        args.quality = -1
        args.resize = 0

    if args.list:
        images = list(list_image(args.root, args.recursive))
        if args.shuffle:
            random.seed(100)
            random.shuffle(images)
        n_train = int(len(images) * args.train_ratio)
        if args.train_ratio < 1.0:
            write_list(args.prefix + "_train.lst", images[:n_train])
            write_list(args.prefix + "_val.lst", images[n_train:])
        else:
            write_list(args.prefix + ".lst", images)
        print(f"wrote list with {len(images)} images")
    elif args.native:
        from mxnet_tpu import native

        tic = time.time()
        n = native.pack_list(
            args.prefix + ".lst", args.root, args.prefix + ".rec",
            args.prefix + ".idx", num_threads=args.num_thread,
            resize=args.resize, quality=args.quality,
        )
        print(f"packed {n} images in {time.time() - tic:.1f}s (native)")
    else:
        im2rec(args.prefix, args.root, args)


if __name__ == "__main__":
    main()
