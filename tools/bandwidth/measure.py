#!/usr/bin/env python
"""Measure gradient-aggregation bandwidth (reference tools/bandwidth/measure.py).

The reference benchmarks kvstore push+pull over its CommDevice/ps-lite
paths. Here the data path is an XLA psum over the device mesh, so this
measures exactly that: allreduce throughput for resnet-sized gradient sets
across all visible devices.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser(description="measure allreduce bandwidth")
    parser.add_argument("--num-arrays", type=int, default=50)
    parser.add_argument("--size-mb", type=float, default=4.0,
                        help="size per gradient array in MB")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--dtype", type=str, default="float32")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("dp",))
    elems = int(args.size_mb * 1e6 / np.dtype(args.dtype).itemsize)
    grads = [
        jax.device_put(
            jnp.ones((n, elems), args.dtype), NamedSharding(mesh, P("dp"))
        )
        for _ in range(args.num_arrays)
    ]

    @jax.jit
    def allreduce(gs):
        return [jnp.broadcast_to(jnp.sum(g, axis=0), g.shape) for g in gs]

    out = allreduce(grads)
    jax.block_until_ready(out)
    tic = time.time()
    for _ in range(args.iters):
        out = allreduce(grads)
    jax.block_until_ready(out)
    dt = (time.time() - tic) / args.iters
    total_bytes = args.num_arrays * elems * np.dtype(args.dtype).itemsize
    print(
        f"devices={n} arrays={args.num_arrays} x {args.size_mb}MB  "
        f"time/iter={dt * 1e3:.2f}ms  algo-bw="
        f"{total_bytes / dt / 1e9:.2f} GB/s"
    )


if __name__ == "__main__":
    main()
