#!/usr/bin/env python
"""accnn — low-rank model compression over the Symbol API.

Reference: ``tools/accnn`` (accnn.py driver + acc_conv.py / acc_fc.py /
rank_selection.py). A KxK Convolution factorizes into a vertical (Kx1)
conv with R filters followed by a horizontal (1xK) conv (the Jaderberg
scheme, exactly the reference's SVD split: W[(c,y),(n,x)] = U S V^T with
V-conv U*sqrt(S) and H-conv sqrt(S)*V^T); a FullyConnected factorizes
into two FCs through an R-dim bottleneck. Rank selection mirrors the
reference's energy-based allocation with a simpler search: a global
retained-energy threshold, binary-searched so the factorized FLOPs hit
the requested speedup (the reference solves the same trade-off with a
knapsack DP over log-energies).

After compression, fine-tune: load the returned (symbol, arg_params)
into a Module and fit a few epochs — the reference README's recipe.

Usage:
    python tools/accnn.py --model prefix --epoch 0 --speedup 2 \\
        --data-shape 3,224,224 --save-model prefix-acc
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _conv_svd(W):
    """Singular values of the (C*KH, N*KW) matricization."""
    N, C, kh, kw = W.shape
    M = W.transpose(1, 2, 0, 3).reshape(C * kh, N * kw)
    return np.linalg.svd(M, compute_uv=False)


def _split_conv_weights(W, rank):
    N, C, kh, kw = W.shape
    M = W.transpose(1, 2, 0, 3).reshape(C * kh, N * kw)
    U, D, Qt = np.linalg.svd(M, full_matrices=False)
    sq = np.sqrt(D[:rank])
    V = (U[:, :rank] * sq).T.reshape(rank, C, kh, 1)
    H = (Qt[:rank].T * sq).reshape(N, kw, 1, rank).transpose(0, 3, 2, 1)
    return V.astype(W.dtype), H.astype(W.dtype)


def _split_fc_weights(W, rank):
    N, M_ = W.shape
    U, D, Qt = np.linalg.svd(W, full_matrices=False)
    sq = np.sqrt(D[:rank])
    W1 = (Qt[:rank].T * sq).T          # (rank, M)
    W2 = U[:, :rank] * sq              # (N, rank)
    return W1.astype(W.dtype), W2.astype(W.dtype)


def _conv_flops(node_params, in_c, out_hw, rank=None):
    kh, kw = node_params["kernel"]
    n = node_params["num_filter"]
    h, w = out_hw
    if rank is None:
        return kh * kw * in_c * n * h * w
    # V: kh*1 over C -> rank, H: 1*kw over rank -> n
    return kh * in_c * rank * h * w + kw * rank * n * h * w


class _Plan:
    __slots__ = ("node", "kind", "svals", "flops_fn", "rank")

    def __init__(self, node, kind, svals, flops_fn):
        self.node = node
        self.kind = kind
        self.svals = svals
        self.flops_fn = flops_fn  # rank|None -> flops
        self.rank = None


def factorize(symbol, arg_params, speedup=2.0, data_shape=(3, 224, 224),
              min_rank=4, skip=()):
    """Compress (symbol, arg_params): returns (new_symbol, new_arg_params,
    report) with report = {layer: (rank, max_rank, kept_energy)}.

    Only stride-compatible KxK convs with K>1 and FullyConnected layers
    factorize; 1x1 convs and layers in ``skip`` pass through."""
    from mxnet_tpu.ops import registry
    from mxnet_tpu.symbol import Symbol, _Node, fromjson

    sym = fromjson(symbol.tojson())
    arg_params = dict(arg_params)

    # internal output shapes for FLOPs accounting
    internals = sym.get_internals()
    _, out_shapes, _ = internals.infer_shape(data=(1,) + tuple(data_shape))
    shape_of = dict(zip(internals.list_outputs(), out_shapes))

    # classifier heads (layers consumed only by loss ops) are excluded by
    # default: their rank IS the class count, so truncating it destroys
    # the model for negligible FLOPs
    consumers = {}
    for n in sym._topo():
        if n.is_variable:
            continue
        for (inp, _ix) in n.inputs:
            consumers.setdefault(id(inp), []).append(n)
    head_feeders = set()
    for n in sym._topo():
        if n.is_variable:
            continue
        cons = consumers.get(id(n), [])
        if cons and all(getattr(c.op, "is_loss", False) for c in cons):
            head_feeders.add(n.name)

    plans = []
    for node in sym._topo():
        if node.is_variable or node.name in skip \
                or node.name in head_feeders:
            continue
        params = node.params()
        wname = f"{node.name}_weight"
        if wname not in arg_params:
            continue
        W = np.asarray(arg_params[wname].asnumpy())
        if node.op.name == "Convolution":
            kh, kw = params["kernel"]
            dil = params.get("dilate") or (1, 1)
            if kh <= 1 or kw <= 1 or params.get("num_group", 1) != 1 \
                    or tuple(dil) != (1, 1):
                continue  # grouped/dilated convs keep their geometry
            out_shape = shape_of.get(f"{node.name}_output")
            if out_shape is None or len(out_shape) != 4:
                continue
            in_c, out_hw = W.shape[1], out_shape[2:]
            p = dict(kernel=(kh, kw), num_filter=params["num_filter"])
            plans.append(_Plan(
                node, "conv", _conv_svd(W),
                lambda r, p=p, c=in_c, o=out_hw: _conv_flops(p, c, o, r)))
        elif node.op.name == "FullyConnected":
            n, m = W.shape
            plans.append(_Plan(
                node, "fc", np.linalg.svd(W, compute_uv=False),
                lambda r, n=n, m=m: n * m if r is None else r * (n + m)))
    if not plans:
        return sym, arg_params, {}

    base_flops = sum(p.flops_fn(None) for p in plans)
    budget = base_flops / float(speedup)

    def ranks_at(tau):
        """Per-layer minimal rank keeping >= tau of the energy."""
        out = []
        for p in plans:
            e = np.cumsum(p.svals ** 2)
            e /= e[-1]
            r = int(np.searchsorted(e, tau) + 1)
            out.append(max(min_rank, min(r, len(p.svals))))
        return out

    lo, hi = 0.0, 1.0
    for _ in range(40):  # binary search the energy threshold to the budget
        mid = (lo + hi) / 2
        cost = sum(p.flops_fn(r) for p, r in zip(plans, ranks_at(mid)))
        if cost > budget:
            hi = mid
        else:
            lo = mid
    ranks = ranks_at(lo)

    convdef = registry.get("Convolution")
    fcdef = registry.get("FullyConnected")
    replaced = {}
    new_nodes = []
    report = {}
    for p, rank in zip(plans, ranks):
        node = p.node
        name = node.name
        params = node.params()
        W = np.asarray(arg_params.pop(f"{name}_weight").asnumpy())
        data_in = node.inputs[0]
        bias_in = None
        if not params.get("no_bias", False):
            bias_in = node.inputs[len(node.op.arg_names(params)) - 1]
        rank = min(rank, len(p.svals))  # min_rank may exceed a tiny layer
        if rank >= len(p.svals):
            # full rank: splitting would only add FLOPs; keep the layer
            arg_params[f"{name}_weight"] = _nd(W)
            report[name] = (len(p.svals), len(p.svals), 1.0)
            continue
        e = np.cumsum(p.svals ** 2)
        report[name] = (rank, len(p.svals), float(e[rank - 1] / e[-1]))
        if p.kind == "conv":
            V, H = _split_conv_weights(W, rank)
            kh, kw = params["kernel"]
            sh, sw = params.get("stride") or (1, 1)
            ph, pw = params.get("pad") or (0, 0)
            v_attrs = {
                "kernel": f"({kh}, 1)", "stride": f"({sh}, 1)",
                "pad": f"({ph}, 0)", "num_filter": str(rank),
                "no_bias": "True",
            }
            v_w = _Node(None, f"{name}_v_weight")
            v_node = _Node(convdef, f"{name}_v", v_attrs,
                           [data_in, (v_w, 0)])
            h_attrs = {
                "kernel": f"(1, {kw})", "stride": f"(1, {sw})",
                "pad": f"(0, {pw})",
                "num_filter": str(params["num_filter"]),
                "no_bias": str(bool(params.get("no_bias", False))),
            }
            h_w = _Node(None, f"{name}_h_weight")
            h_inputs = [(v_node, 0), (h_w, 0)]
            if bias_in is not None:
                h_inputs.append(bias_in)
            h_node = _Node(convdef, f"{name}_h", h_attrs, h_inputs)
            arg_params[f"{name}_v_weight"] = _nd(V)
            arg_params[f"{name}_h_weight"] = _nd(H)
            replaced[id(node)] = h_node
            new_nodes.append(v_node)
        else:
            W1, W2 = _split_fc_weights(W, rank)
            f1_attrs = {"num_hidden": str(rank), "no_bias": "True",
                        "flatten": str(bool(params.get("flatten", True)))}
            f1_w = _Node(None, f"{name}_v_weight")
            f1 = _Node(fcdef, f"{name}_v", f1_attrs, [data_in, (f1_w, 0)])
            f2_attrs = {
                "num_hidden": str(params["num_hidden"]),
                "no_bias": str(bool(params.get("no_bias", False))),
                "flatten": "True",  # f1's output is already 2-d
            }
            f2_w = _Node(None, f"{name}_h_weight")
            f2_inputs = [(f1, 0), (f2_w, 0)]
            if bias_in is not None:
                f2_inputs.append(bias_in)
            f2 = _Node(fcdef, f"{name}_h", f2_attrs, f2_inputs)
            arg_params[f"{name}_v_weight"] = _nd(W1)
            arg_params[f"{name}_h_weight"] = _nd(W2)
            replaced[id(node)] = f2
            new_nodes.append(f1)

    if replaced:
        # rewire every consumer edge and the heads; the fresh v/fc1 nodes
        # also consume old edges (conv->conv chains), so include them
        for node in sym._topo() + new_nodes:
            node.inputs = [
                (replaced.get(id(n), n), ix) for (n, ix) in node.inputs
            ]
        sym._outputs = [
            (replaced.get(id(n), n), ix) for (n, ix) in sym._outputs
        ]
    return sym, arg_params, report


def _nd(a):
    from mxnet_tpu.ndarray import array

    return array(np.ascontiguousarray(a))


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", required=True, help="checkpoint prefix")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--speedup", type=float, default=2.0)
    ap.add_argument("--data-shape", default="3,224,224")
    ap.add_argument("--min-rank", type=int, default=4)
    ap.add_argument("--save-model", required=True)
    args = ap.parse_args()

    import mxnet_tpu as mx

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.model, args.epoch)
    shape = tuple(int(x) for x in args.data_shape.split(","))
    new_sym, new_args, report = factorize(
        sym, arg_params, speedup=args.speedup, data_shape=shape,
        min_rank=args.min_rank)
    for layer, (rank, full, kept) in sorted(report.items()):
        print(f"{layer}: rank {rank}/{full} ({100 * kept:.1f}% energy)")
    mx.model.save_checkpoint(args.save_model, 0, new_sym, new_args,
                             aux_params)
    print(f"wrote {args.save_model}-symbol.json / -0000.params "
          f"(fine-tune with Module.fit to recover accuracy)")


if __name__ == "__main__":
    main()
