#!/usr/bin/env python
"""Launch multi-host training (reference tools/launch.py → dmlc tracker).

The reference spawns worker/server/scheduler processes over ssh/mpi/yarn and
rendezvouses via env vars (DMLC_ROLE etc.). On TPU the launch model is one
process per host, all running the SAME SPMD program, rendezvousing through
the jax distributed runtime — there are no parameter servers to start.

  python tools/launch.py -n 4 -H hostfile python train_imagenet.py ...
  → runs the command on every host with MXNET_COORDINATOR/MXNET_NUM_PROCS/
    MXNET_PROC_ID set; mxnet_tpu initialises jax.distributed from those.

--launcher local spawns the processes locally (the reference's local tracker
used by the nightly dist tests).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def _alloc_ps_port(coordinator):
    """Pick the dist_async parameter-server port for this job.

    When the coordinator host is local the port is allocated by binding
    with SO_REUSEPORT and HOLDING the socket for the launcher's lifetime,
    so the ephemeral port cannot be handed to another process before (or
    while) rank 0's server binds it with its own SO_REUSEPORT socket (the
    launcher's bound-but-not-listening socket never receives connections).
    For remote coordinators fall back to the deterministic
    coordinator-port+512 convention. Either way the chosen port is
    exported as MXNET_PS_PORT so workers and server agree by construction.

    Returns (port, holder_socket_or_None); the caller keeps the holder
    referenced for the job's duration."""
    import socket

    host, port = coordinator.rsplit(":", 1)
    if host in ("127.0.0.1", "localhost", "0.0.0.0") and \
            hasattr(socket, "SO_REUSEPORT"):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, 0))
        return s.getsockname()[1], s
    return int(port) + 512, None


def _job_security_env():
    """A per-job random HMAC key for the dist_async wire protocol, unless
    the operator already provided one."""
    if os.environ.get("MXNET_PS_KEY"):
        return {}
    import secrets

    return {"MXNET_PS_KEY": secrets.token_hex(32)}


def _scrub_axon_env(env, num_workers):
    """Drop the single-chip axon tunnel boot vars from a local multi-worker
    job's environment.

    The deployment's sitecustomize dials the axon pool in every interpreter
    at boot when ``PALLAS_AXON_POOL_IPS`` (and siblings) are set, and the
    pool holds ONE chip session: with N>1 local workers, every worker past
    the first spins forever in the chip-claim retry loop instead of
    starting (the 300 s hang mode diagnosed in VERDICT r5). Local
    multi-worker jobs are CPU/virtual-mesh jobs by construction — one chip
    cannot back N ranks — so the boot vars are scrubbed rather than raced
    for. Single-worker jobs keep them: the lone rank is the legitimate
    claimant.
    """
    if num_workers > 1:
        for k in [k for k in env if k.startswith("PALLAS_AXON_")]:
            env.pop(k, None)
    return env


def _worker_env(rank, num_workers, coordinator, num_restarts=0,
                job_env=None):
    env = _scrub_axon_env(dict(os.environ), num_workers)
    env.update({
        "MXNET_COORDINATOR": coordinator,
        "MXNET_NUM_PROCS": str(num_workers),
        "MXNET_PROC_ID": str(rank),
        # how many times the supervisor has restarted the job — surfaced
        # to workers so kvstore.num_dead_node can report reality
        "MXNET_NUM_RESTARTS": str(num_restarts),
        # reference-compatible names some scripts read:
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_WORKER_ID": str(rank),
    })
    # supervised jobs should never hang silently in a dead-peer collective:
    # the kvstore watchdog turns a stalled barrier into a clean exit the
    # supervisor restarts (and, with MXNET_CHECKPOINT_DIR, a mid-training
    # resume). Operators can override or disable (0) explicitly.
    env.setdefault("MXNET_KV_TIMEOUT", "600")
    env.update(job_env or {})
    return env


def _supervise_local(command, num_workers, coordinator, max_restarts):
    """Run + monitor local workers; restart the JOB on any rank failure
    (the launcher-level failure detection the reference gets from the
    ps-lite scheduler's liveness tracking + is_recovery restart path,
    kvstore_dist.h:177-195).

    Restarts are whole-job: the jax distributed runtime cannot re-admit a
    single restarted rank while the surviving ranks sit stalled in a
    collective (and if rank 0 dies, the coordination service dies with it),
    so a per-rank restart would deadlock until timeout. Instead any
    non-zero exit terminates every rank and relaunches all of them, up to
    ``max_restarts`` times; mid-training progress survives via the scripts'
    own checkpoint/resume (--load-epoch pattern). Each attempt advances the
    coordinator port (stale-socket avoidance) and exports
    MXNET_NUM_RESTARTS so workers can report the recovery count.
    """
    import time

    host, port0 = coordinator.rsplit(":", 1)
    attempt = 0
    job_env = _job_security_env()
    holders = []  # keep allocated PS ports reserved for the job's lifetime
    while True:
        coord = f"{host}:{int(port0) + attempt}"
        ps_port, holder = _alloc_ps_port(coord)
        holders.append(holder)
        job_env["MXNET_PS_PORT"] = str(ps_port)
        procs = {
            rank: subprocess.Popen(
                command,
                env=_worker_env(rank, num_workers, coord, attempt, job_env),
            )
            for rank in range(num_workers)
        }
        failed_rank = None
        while procs:
            time.sleep(0.2)
            for rank, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del procs[rank]
                if rc == 0:
                    continue
                failed_rank = (rank, rc)
                for q in procs.values():
                    q.terminate()
                for q in procs.values():
                    try:
                        q.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        q.kill()
                        q.wait()
                procs.clear()
                break
        if failed_rank is None:
            return 0
        rank, rc = failed_rank
        if attempt >= max_restarts:
            sys.stderr.write(
                f"launch.py: rank {rank} died (rc={rc}), restart budget "
                f"spent ({max_restarts}) — job failed\n"
            )
            return 1
        attempt += 1
        sys.stderr.write(
            f"launch.py: rank {rank} died (rc={rc}); whole-job restart "
            f"{attempt}/{max_restarts}\n"
        )


def _supervise_elastic(command, num_workers, coordinator, max_restarts):
    """Per-rank restart supervision for the elastic membership plane
    (``--elastic``): exports ``MXNET_KV_TRANSPORT=tcp`` so the job runs on
    the live-membership kvstore, under which a single dead rank is NOT a
    job death — survivors reshard to dp−1 and keep training, so only the
    dead rank is relaunched, with its OLD rank id (it re-joins as the same
    member), its per-rank ``MXNET_NUM_RESTARTS`` bumped, and the
    coordinator/PS-port env preserved (the launcher's port-holder socket
    keeps the address reserved across the restart).

    Contrast with :func:`_supervise_local`: there the jax runtime pins the
    world, so any death forces a whole-job relaunch on a fresh port; here
    the membership table absorbs the churn and the job never loses the
    survivors' progress.
    """
    import time

    job_env = _job_security_env()
    job_env["MXNET_KV_TRANSPORT"] = "tcp"
    ps_port, _holder = _alloc_ps_port(coordinator)
    job_env["MXNET_PS_PORT"] = str(ps_port)
    restarts = {rank: 0 for rank in range(num_workers)}
    spent = 0

    def _spawn(rank):
        return subprocess.Popen(
            command,
            env=_worker_env(rank, num_workers, coordinator,
                            restarts[rank], job_env),
        )

    procs = {rank: _spawn(rank) for rank in range(num_workers)}
    while procs:
        time.sleep(0.2)
        for rank, p in list(procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            del procs[rank]
            if rc == 0:
                continue
            if spent >= max_restarts:
                sys.stderr.write(
                    f"launch.py: rank {rank} died (rc={rc}), restart "
                    f"budget spent ({max_restarts}) — job failed\n")
                for q in procs.values():
                    q.terminate()
                for q in procs.values():
                    try:
                        q.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        q.kill()
                        q.wait()
                return 1
            spent += 1
            restarts[rank] += 1
            sys.stderr.write(
                f"launch.py: rank {rank} died (rc={rc}); per-rank "
                f"restart (attempt {restarts[rank]}, budget "
                f"{spent}/{max_restarts})\n")
            procs[rank] = _spawn(rank)
    return 0


def main():
    parser = argparse.ArgumentParser(description="Launch a distributed job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-H", "--hostfile", type=str, default=None)
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("--port", type=int, default=9127)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="whole-job restarts after any rank failure "
                             "(local launcher); with --elastic, total "
                             "per-rank restarts")
    parser.add_argument("--elastic", action="store_true",
                        help="run on the elastic membership plane "
                             "(MXNET_KV_TRANSPORT=tcp): a dead rank is "
                             "relaunched alone with its old rank id while "
                             "survivors keep training (local launcher)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    hosts = ["127.0.0.1"] * args.num_workers
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [l.strip() for l in f if l.strip()]
        assert len(hosts) >= args.num_workers

    coordinator = f"{hosts[0]}:{args.port}"
    if args.launcher == "local":
        if args.elastic:
            sys.exit(_supervise_elastic(
                args.command, args.num_workers, coordinator,
                args.max_restarts
            ))
        sys.exit(_supervise_local(
            args.command, args.num_workers, coordinator, args.max_restarts
        ))

    job_env = _job_security_env()
    ps_port, _ps_holder = _alloc_ps_port(coordinator)
    job_env["MXNET_PS_PORT"] = str(ps_port)
    procs = []
    for rank in range(args.num_workers):
        env = _worker_env(rank, args.num_workers, coordinator,
                          job_env=job_env)
        remote_env = " ".join(
            f"{k}={v}" for k, v in env.items()
            if k.startswith(("MXNET_", "DMLC_")) and k != "MXNET_PS_KEY"
        )
        # the HMAC secret must never ride the command line (argv is world-
        # readable via ps on both ends); feed it through ssh stdin instead
        key = env.get("MXNET_PS_KEY", "")
        key_prefix = "IFS= read -r MXNET_PS_KEY; export MXNET_PS_KEY; " \
            if key else ""
        cmd = ["ssh", hosts[rank],
               f"{key_prefix}cd {os.getcwd()} && {remote_env} "
               f"{' '.join(args.command)}"]
        p = subprocess.Popen(cmd, stdin=subprocess.PIPE if key else None)
        if key:
            p.stdin.write((key + "\n").encode())
            p.stdin.close()
        procs.append(p)

    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    sys.exit(code)


if __name__ == "__main__":
    main()
