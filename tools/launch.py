#!/usr/bin/env python
"""Launch multi-host training (reference tools/launch.py → dmlc tracker).

The reference spawns worker/server/scheduler processes over ssh/mpi/yarn and
rendezvouses via env vars (DMLC_ROLE etc.). On TPU the launch model is one
process per host, all running the SAME SPMD program, rendezvousing through
the jax distributed runtime — there are no parameter servers to start.

  python tools/launch.py -n 4 -H hostfile python train_imagenet.py ...
  → runs the command on every host with MXNET_COORDINATOR/MXNET_NUM_PROCS/
    MXNET_PROC_ID set; mxnet_tpu initialises jax.distributed from those.

--launcher local spawns the processes locally (the reference's local tracker
used by the nightly dist tests).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def _worker_env(rank, num_workers, coordinator, num_restarts=0):
    env = dict(os.environ)
    env.update({
        "MXNET_COORDINATOR": coordinator,
        "MXNET_NUM_PROCS": str(num_workers),
        "MXNET_PROC_ID": str(rank),
        # how many times the supervisor has restarted the job — surfaced
        # to workers so kvstore.num_dead_node can report reality
        "MXNET_NUM_RESTARTS": str(num_restarts),
        # reference-compatible names some scripts read:
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_WORKER_ID": str(rank),
    })
    return env


def _supervise_local(command, num_workers, coordinator, max_restarts):
    """Run + monitor local workers; restart the JOB on any rank failure
    (the launcher-level failure detection the reference gets from the
    ps-lite scheduler's liveness tracking + is_recovery restart path,
    kvstore_dist.h:177-195).

    Restarts are whole-job: the jax distributed runtime cannot re-admit a
    single restarted rank while the surviving ranks sit stalled in a
    collective (and if rank 0 dies, the coordination service dies with it),
    so a per-rank restart would deadlock until timeout. Instead any
    non-zero exit terminates every rank and relaunches all of them, up to
    ``max_restarts`` times; mid-training progress survives via the scripts'
    own checkpoint/resume (--load-epoch pattern). Each attempt advances the
    coordinator port (stale-socket avoidance) and exports
    MXNET_NUM_RESTARTS so workers can report the recovery count.
    """
    import time

    host, port0 = coordinator.rsplit(":", 1)
    attempt = 0
    while True:
        coord = f"{host}:{int(port0) + attempt}"
        procs = {
            rank: subprocess.Popen(
                command,
                env=_worker_env(rank, num_workers, coord, attempt),
            )
            for rank in range(num_workers)
        }
        failed_rank = None
        while procs:
            time.sleep(0.2)
            for rank, p in list(procs.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del procs[rank]
                if rc == 0:
                    continue
                failed_rank = (rank, rc)
                for q in procs.values():
                    q.terminate()
                for q in procs.values():
                    try:
                        q.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        q.kill()
                        q.wait()
                procs.clear()
                break
        if failed_rank is None:
            return 0
        rank, rc = failed_rank
        if attempt >= max_restarts:
            sys.stderr.write(
                f"launch.py: rank {rank} died (rc={rc}), restart budget "
                f"spent ({max_restarts}) — job failed\n"
            )
            return 1
        attempt += 1
        sys.stderr.write(
            f"launch.py: rank {rank} died (rc={rc}); whole-job restart "
            f"{attempt}/{max_restarts}\n"
        )


def main():
    parser = argparse.ArgumentParser(description="Launch a distributed job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-H", "--hostfile", type=str, default=None)
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("--port", type=int, default=9127)
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="whole-job restarts after any rank failure "
                             "(local launcher)")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    hosts = ["127.0.0.1"] * args.num_workers
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [l.strip() for l in f if l.strip()]
        assert len(hosts) >= args.num_workers

    coordinator = f"{hosts[0]}:{args.port}"
    if args.launcher == "local":
        sys.exit(_supervise_local(
            args.command, args.num_workers, coordinator, args.max_restarts
        ))

    procs = []
    for rank in range(args.num_workers):
        env = _worker_env(rank, args.num_workers, coordinator)
        remote_env = " ".join(
            f"{k}={v}" for k, v in env.items()
            if k.startswith(("MXNET_", "DMLC_"))
        )
        cmd = ["ssh", hosts[rank],
               f"cd {os.getcwd()} && {remote_env} {' '.join(args.command)}"]
        procs.append(subprocess.Popen(cmd))

    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    sys.exit(code)


if __name__ == "__main__":
    main()
