#!/usr/bin/env python
"""Launch multi-host training (reference tools/launch.py → dmlc tracker).

The reference spawns worker/server/scheduler processes over ssh/mpi/yarn and
rendezvouses via env vars (DMLC_ROLE etc.). On TPU the launch model is one
process per host, all running the SAME SPMD program, rendezvousing through
the jax distributed runtime — there are no parameter servers to start.

  python tools/launch.py -n 4 -H hostfile python train_imagenet.py ...
  → runs the command on every host with MXNET_COORDINATOR/MXNET_NUM_PROCS/
    MXNET_PROC_ID set; mxnet_tpu initialises jax.distributed from those.

--launcher local spawns the processes locally (the reference's local tracker
used by the nightly dist tests).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def main():
    parser = argparse.ArgumentParser(description="Launch a distributed job")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-H", "--hostfile", type=str, default=None)
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("--port", type=int, default=9127)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    hosts = ["127.0.0.1"] * args.num_workers
    if args.hostfile:
        with open(args.hostfile) as f:
            hosts = [l.strip() for l in f if l.strip()]
        assert len(hosts) >= args.num_workers

    coordinator = f"{hosts[0]}:{args.port}"
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "MXNET_COORDINATOR": coordinator,
            "MXNET_NUM_PROCS": str(args.num_workers),
            "MXNET_PROC_ID": str(rank),
            # reference-compatible names some scripts read:
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(rank),
        })
        if args.launcher == "local":
            procs.append(subprocess.Popen(args.command, env=env))
        else:
            remote_env = " ".join(
                f"{k}={v}" for k, v in env.items()
                if k.startswith(("MXNET_", "DMLC_"))
            )
            cmd = ["ssh", hosts[rank],
                   f"cd {os.getcwd()} && {remote_env} {' '.join(args.command)}"]
            procs.append(subprocess.Popen(cmd))

    code = 0
    for p in procs:
        p.wait()
        code = code or p.returncode
    sys.exit(code)


if __name__ == "__main__":
    main()
