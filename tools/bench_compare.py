#!/usr/bin/env python
"""Diff two bench JSON records and gate on throughput regressions.

The CI answer to "did this PR slow anything down?": bench.py (any
BENCH_MODE, including the whole-zoo ``suite`` scoreboard) prints one JSON
line; save the line from the base revision and the candidate, then:

  python tools/bench_compare.py base.json new.json --threshold 5

exits 1 when any gated metric regressed by more than the threshold
percent. Stdlib only — usable from any CI image that can run python.

Metric selection
----------------
By default every numeric field that is throughput-shaped is gated,
discovered by walking both records and matching leaf names:

* higher-is-better: ``value``, ``*_per_sec``, ``mfu*``, ``vs_baseline``,
  ``fused_speedup``, ``availability``, ``replica_scaling``,
  ``group_scaling_4x``, ``pool_speedup`` (the BENCH_MODE=io decode-pool
  vs serial ratio) — regression = new < base.
* lower-is-better: ``steady_compiles`` (the zero-recompile invariant:
  ANY increase past the threshold fails), plus any path named via
  ``--lower-better``.

``--metrics workloads.dcgan.train_samples_per_sec,value`` restricts the
gate to explicit dotted paths (a path missing from either record is an
error — a silently vanished metric must not pass). Fields present in only
one record are reported as added/removed but never gate, so a bench
record can grow new fields without breaking older baselines.

``kernels`` tables (the top-10 per-kernel device-time attribution bench
embeds in fit/suite records) are diffed by membership: a kernel newly
entering or leaving a top-10 is reported in the notes with its share of
device time — the "where did the step time move" pointer — but never
gates, since XLA renames fusions across otherwise-identical compiles.

A bench file may hold whole driver output; the LAST line that parses as a
JSON object is the record (bench.py's output contract).
"""

import argparse
import json
import sys

_HIGHER_LEAVES = ("value", "vs_baseline", "fused_speedup", "availability",
                  "replica_scaling", "group_scaling_4x", "pool_speedup")
_HIGHER_PREFIXES = ("mfu",)
_HIGHER_SUFFIXES = ("_per_sec",)
_LOWER_LEAVES = ("steady_compiles",)


def load_record(path):
    """Last JSON-object line of the file — bench.py prints exactly one."""
    record = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                record = parsed
    if record is None:
        raise SystemExit(f"{path}: no JSON record line found")
    return record


def walk(obj, prefix=""):
    """Yield (dotted_path, number) for every numeric leaf."""
    if isinstance(obj, dict):
        for key, val in obj.items():
            yield from walk(val, f"{prefix}.{key}" if prefix else key)
    elif isinstance(obj, bool):
        return
    elif isinstance(obj, (int, float)):
        yield prefix, float(obj)


def kernel_tables(record, prefix=""):
    """Yield (dotted_path, {kernel_name: row}) for every embedded top-10
    kernel table (``"kernels"`` lists of {name, device_us, pct} rows)."""
    if not isinstance(record, dict):
        return
    for key, val in record.items():
        path = f"{prefix}.{key}" if prefix else key
        if key == "kernels" and isinstance(val, list):
            yield path, {r["name"]: r for r in val
                         if isinstance(r, dict) and "name" in r}
        else:
            yield from kernel_tables(val, path)


def diff_kernels(base, new):
    """Notes naming kernels that newly entered / left each top-10 table
    present in both records (informational — never gates)."""
    base_tables = dict(kernel_tables(base))
    notes = []
    for path, rows in kernel_tables(new):
        old = base_tables.get(path)
        if old is None or not old:
            continue
        entered = [n for n in rows if n not in old]
        left = [n for n in old if n not in rows]
        if entered:
            detail = ", ".join(
                f"{n} ({100.0 * rows[n].get('pct', 0.0):.1f}% of step)"
                for n in entered[:5])
            notes.append(f"{path}: newly in top-10: {detail}"
                         + (" ..." if len(entered) > 5 else ""))
        if left:
            notes.append(f"{path}: left top-10: {', '.join(left[:5])}"
                         + (" ..." if len(left) > 5 else ""))
    return notes


def lookup(record, path):
    node = record
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def classify(path):
    """'higher', 'lower' or None (not gated by default)."""
    leaf = path.rsplit(".", 1)[-1]
    if leaf in _LOWER_LEAVES:
        return "lower"
    if (leaf in _HIGHER_LEAVES or leaf.endswith(_HIGHER_SUFFIXES)
            or leaf.startswith(_HIGHER_PREFIXES)):
        return "higher"
    return None


def compare(base, new, threshold, metrics=None, lower_better=()):
    """Returns (rows, regressions, notes). Each row is
    (path, base, new, delta_pct, direction)."""
    base_paths = dict(walk(base))
    new_paths = dict(walk(new))
    if metrics:
        gated = []
        for path in metrics:
            if lookup(base, path) is None or lookup(new, path) is None:
                raise SystemExit(f"--metrics {path}: not a numeric field of "
                                 f"both records")
            gated.append(path)
    else:
        gated = sorted(p for p in base_paths
                       if p in new_paths and classify(p) is not None)
    rows, regressions = [], []
    for path in gated:
        b, n = lookup(base, path), lookup(new, path)
        direction = ("lower" if path in lower_better
                     else classify(path) or "higher")
        if b == 0.0:
            # zero base: any increase of a lower-is-better metric (e.g.
            # steady_compiles 0 -> 1) is an unbounded regression
            delta = 0.0 if n == b else float("inf")
            regressed = direction == "lower" and n > b
        else:
            delta = (n - b) / abs(b) * 100.0
            regressed = (delta < -threshold if direction == "higher"
                         else delta > threshold)
        rows.append((path, b, n, delta, direction))
        if regressed:
            regressions.append(path)
    notes = diff_kernels(base, new)
    only_base = sorted(set(base_paths) - set(new_paths))
    only_new = sorted(set(new_paths) - set(base_paths))
    if only_base:
        notes.append(f"removed: {', '.join(only_base[:8])}"
                     + (" ..." if len(only_base) > 8 else ""))
    if only_new:
        notes.append(f"added: {', '.join(only_new[:8])}"
                     + (" ..." if len(only_new) > 8 else ""))
    return rows, regressions, notes


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="gate bench JSON records on throughput regressions")
    parser.add_argument("base", help="bench output at the base revision")
    parser.add_argument("new", help="bench output at the candidate revision")
    parser.add_argument("--threshold", type=float, default=5.0,
                        help="max tolerated regression, percent (default 5)")
    parser.add_argument("--metrics", type=str, default=None,
                        help="comma-separated dotted paths to gate "
                             "(default: auto-discover throughput fields)")
    parser.add_argument("--lower-better", type=str, default="",
                        help="comma-separated dotted paths where an "
                             "INCREASE is the regression")
    args = parser.parse_args(argv)

    metrics = ([m.strip() for m in args.metrics.split(",") if m.strip()]
               if args.metrics else None)
    lower = tuple(m.strip() for m in args.lower_better.split(",")
                  if m.strip())
    rows, regressions, notes = compare(
        load_record(args.base), load_record(args.new), args.threshold,
        metrics=metrics, lower_better=lower)

    if not rows:
        raise SystemExit("no comparable metrics between the two records")
    width = max(len(r[0]) for r in rows)
    for path, b, n, delta, direction in rows:
        flag = " <-- REGRESSION" if path in regressions else ""
        arrow = "v" if direction == "lower" else "^"
        print(f"{path:<{width}}  {b:>12.3f} -> {n:>12.3f}  "
              f"{delta:>+8.2f}% ({arrow}){flag}")
    for note in notes:
        print(note)
    if regressions:
        print(f"FAIL: {len(regressions)} metric(s) regressed more than "
              f"{args.threshold}%: {', '.join(regressions)}")
        return 1
    print(f"OK: {len(rows)} metric(s) within {args.threshold}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
