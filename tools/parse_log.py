#!/usr/bin/env python
"""Parse training logs into a table (reference tools/parse_log.py)."""

from __future__ import annotations

import argparse
import re
import sys


def main():
    parser = argparse.ArgumentParser(description="Parse mxnet_tpu training logs")
    parser.add_argument("logfile", help="log file path")
    parser.add_argument("--format", type=str, default="markdown",
                        choices=["markdown", "none"])
    args = parser.parse_args()

    with open(args.logfile) as f:
        lines = f.readlines()

    res = [
        re.compile(r"Epoch\[(\d+)\] Train-([^=]+)=([.\d]+)"),
        re.compile(r"Epoch\[(\d+)\] Validation-([^=]+)=([.\d]+)"),
        re.compile(r"Epoch\[(\d+)\] Time cost=([.\d]+)"),
    ]
    data = {}
    for l in lines:
        m = res[0].search(l)
        if m:
            data.setdefault(int(m.group(1)), {})[f"train-{m.group(2)}"] = float(m.group(3))
        m = res[1].search(l)
        if m:
            data.setdefault(int(m.group(1)), {})[f"val-{m.group(2)}"] = float(m.group(3))
        m = res[2].search(l)
        if m:
            data.setdefault(int(m.group(1)), {})["time"] = float(m.group(2))

    if not data:
        print("no epoch records found", file=sys.stderr)
        return
    cols = sorted({k for v in data.values() for k in v})
    if args.format == "markdown":
        print("| epoch | " + " | ".join(cols) + " |")
        print("| --- " * (len(cols) + 1) + "|")
        for epoch in sorted(data):
            row = [f"{data[epoch].get(c, float('nan')):.6g}" for c in cols]
            print(f"| {epoch} | " + " | ".join(row) + " |")
    else:
        for epoch in sorted(data):
            print(epoch, data[epoch])


if __name__ == "__main__":
    main()
