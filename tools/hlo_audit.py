#!/usr/bin/env python
"""Donation/upcast audit of the fused train-update window program.

The fused window (executor.fused_train_update) donates every steady-state
buffer — parameters, aux, optimizer state, hyper tape, guard counters — so
the whole train step updates in place with zero extra parameter-sized
writes. That contract is easy to silently lose: a dtype change, a dropped
return, or a new carry added without a matching output turns a donation
into a copy (jax warns once, nobody reads it) and the step quietly grows
an HBM round-trip per parameter. Likewise the bf16 master-weight recipe
(models/recipe.py) promises exactly one bf16→f32 promotion per parameter
per step — the gradient cast folded into the update epilogue; any further
parameter-sized f32 upcast means the master-weight rule regressed.

This tool pins both on the *lowered evidence*, not the implementation:

- **Donation audit** — every buffer the executor donated must surface in
  the ``@main`` signature of the lowered StableHLO as either
  ``tf.aliasing_output`` (jax matched it to an output at lowering time) or
  ``jax.buffer_donor`` (left for the compiler to place); donors must then
  land in the executable's ``input_output_alias`` table. A donated leaf
  with *neither* marker is a donation jax dropped (shape/dtype mismatch
  with every output — the silent-copy case), and fails the audit.
- **Upcast audit** — in the lowered StableHLO (jax-traced casts only; the
  backend's own compute-precision converts are out of scope),
  ``bf16→f32 stablehlo.convert`` ops whose shape equals an updated
  parameter's shape are counted per shape. The master-weight recipe emits
  exactly one per parameter per window step (the gradient promotion), so
  more than ``--max-upcasts-per-param`` (default 1) × window × parameters
  of that shape fails. Activation-shaped f32 math (BatchNorm statistics)
  is deliberately out of scope.

Run it as a CLI (builds the fused ResNet window on the default backend,
prints a JSON verdict, exit 1 on failure)::

    python tools/hlo_audit.py [--layers 50] [--image 3,32,32] [--batch 4]
                              [--dtype bfloat16] [--window 2] [--json out]

or import :func:`audit` / :func:`audit_current` from tests with a record
from ``mxnet_tpu.executor.fused_window_hlo()``.
"""

import argparse
import json
import os
import re
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

_ARG_SPLIT_RE = re.compile(r"%arg(\d+):")
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(\w+-alias)\)")
_UPCAST_RE = re.compile(
    r"stablehlo\.convert[^\n]*\(tensor<([0-9x]+)xbf16>\)"
    r"\s*->\s*tensor<\1xf32>")


def _main_signature(lowered_text):
    """The argument list of ``func.func public @main(...)`` — inner
    functions (while bodies etc.) declare their own %argN and must not be
    scanned."""
    i = lowered_text.find("@main(")
    if i < 0:
        return ""
    # the signature ends at the "{" that opens the body; attribute dicts
    # inside the signature never put their closing brace at end-of-token
    # position " {" followed by a newline, the body opener does
    j = lowered_text.find("{\n", i)
    return lowered_text[i:j if j > 0 else len(lowered_text)]


def main_donation_marks(lowered_text):
    """``(aliased, donors)`` arg-index sets of @main: args jax already
    matched to an output (``tf.aliasing_output``) and args left for the
    compiler to place (``jax.buffer_donor``).

    Parsed per argument chunk rather than by an attribute-dict regex —
    attribute values may nest braces (``mhlo.sharding = "{replicated}"``)
    which defeats any ``\\{[^}]*\\}`` pattern.
    """
    parts = _ARG_SPLIT_RE.split(_main_signature(lowered_text))
    aliased, donors = set(), set()
    # parts = [prefix, idx, chunk, idx, chunk, ...]
    for k in range(1, len(parts) - 1, 2):
        idx, chunk = int(parts[k]), parts[k + 1]
        if "tf.aliasing_output" in chunk:
            aliased.add(idx)
        elif "jax.buffer_donor" in chunk:
            donors.add(idx)
    return aliased, donors


def compiled_aliased_params(compiled_text):
    """Parameter indices in the executable's ``input_output_alias`` table.

    The table is brace-nested (``{ {1}: (21, {}, may-alias), ... }``) so
    its extent is found by brace counting, not a non-greedy regex.
    """
    key = "input_output_alias={"
    i = compiled_text.find(key)
    if i < 0:
        return set()
    start = i + len(key)
    depth, j = 1, start
    while j < len(compiled_text) and depth:
        depth += {"{": 1, "}": -1}.get(compiled_text[j], 0)
        j += 1
    table = compiled_text[start:j]
    return {int(e.group(1)) for e in _ALIAS_ENTRY_RE.finditer(table)}


def param_sized_upcasts(lowered_text, param_shapes):
    """{shape: count} of jax-traced bf16→f32 converts whose dims equal an
    updated parameter's shape (the gradient-promotion casts)."""
    want = {"x".join(str(d) for d in s) for s in param_shapes}
    counts = {}
    for m in _UPCAST_RE.finditer(lowered_text):
        dims = m.group(1)
        if dims in want:
            counts[dims] = counts.get(dims, 0) + 1
    return counts


def audit(record, max_upcasts_per_param=1, steps=1):
    """Audit a ``fused_window_hlo()`` record. Returns a verdict dict with
    ``ok``, per-check results, and the offending counts/shapes.

    ``steps`` is the window length the program was traced for — the
    master-weight recipe legitimately promotes each gradient once per
    step, so the upcast allowance scales with it.
    """
    donated = len(record["donated_args"])
    aliased, donors = main_donation_marks(record["lowered"])
    compiled = compiled_aliased_params(record["compiled"])
    # donors the compiler never placed in the alias table
    unaliased = sorted(donors - compiled)
    # donated leaves that reached @main with neither marker: jax dropped
    # the donation entirely (no output of matching shape/dtype)
    dropped = donated - len(aliased) - len(donors)

    shapes = [tuple(s) for s in record["param_shapes"]]
    per_shape = {}
    for s in shapes:
        key = "x".join(str(d) for d in s)
        per_shape[key] = per_shape.get(key, 0) + 1
    upcasts = param_sized_upcasts(record["lowered"], shapes)
    allowance = max_upcasts_per_param * max(1, int(steps))
    stray = {
        dims: n for dims, n in upcasts.items()
        if n > allowance * per_shape.get(dims, 0)
    }

    return {
        "ok": not unaliased and dropped <= 0 and not stray,
        "donated_args": donated,
        "aliased_args": len(aliased),
        "donor_args": len(donors),
        "dropped_donations": max(0, dropped),
        "unaliased_donations": unaliased,
        "param_count": len(shapes),
        "param_sized_upcasts": upcasts,
        "stray_upcasts": stray,
        "max_upcasts_per_param": max_upcasts_per_param,
        "steps": int(steps),
    }


def audit_current(**kw):
    """Audit the most recent fused-window compile in this process."""
    from mxnet_tpu.executor import fused_window_hlo

    rec = fused_window_hlo()
    if rec is None:
        raise RuntimeError(
            "no fused window has been compiled in this process "
            "(run a train_window first, with the AOT disk cache off)")
    return audit(rec, **kw)


def _build_and_run(layers, image, batch, dtype, window):
    """Compile + run one fused ResNet train window so the executor records
    its program."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.models.resnet import get_symbol

    net = get_symbol(num_classes=10, num_layers=layers, image_shape=image,
                     dtype=dtype)
    shape = (batch,) + tuple(int(x) for x in image.split(","))
    mod = mx.mod.Module(net, context=mx.cpu() if mx.context.num_gpus() == 0
                        else mx.gpu())
    mod.bind(data_shapes=[mx.io.DataDesc("data", shape)],
             label_shapes=[mx.io.DataDesc("softmax_label", (batch,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)
    b = mx.io.DataBatch(
        data=[mx.nd.array(rng.rand(*shape).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, 10, (batch,)).astype(np.float32))])
    mod.train_window(b, window, publish_grads=False).wait()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="audit donation aliasing + master-weight upcasts of "
                    "the fused train window")
    ap.add_argument("--layers", type=int, default=50)
    ap.add_argument("--image", default="3,32,32")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--window", type=int, default=2)
    ap.add_argument("--max-upcasts-per-param", type=int, default=1)
    ap.add_argument("--json", help="also write the verdict to this path")
    args = ap.parse_args(argv)

    # the audit needs a fresh lowering: a disk-cached executable skips it
    os.environ["MXNET_AOT_CACHE"] = "0"
    _build_and_run(args.layers, args.image, args.batch, args.dtype,
                   args.window)
    verdict = audit_current(max_upcasts_per_param=args.max_upcasts_per_param,
                            steps=args.window)
    verdict["workload"] = (f"resnet-{args.layers}@{args.image} "
                           f"bs{args.batch} {args.dtype} K={args.window}")
    out = json.dumps(verdict, indent=2, sort_keys=True)
    print(out)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
