#!/usr/bin/env python
"""Serve a model over HTTP: dynamic batching + bucketed AOT inference.

Stdlib-only CLI over :mod:`mxnet_tpu.serving`. Examples::

    # from a save_checkpoint prefix (prefix-symbol.json + prefix-0000.params)
    python tools/serve.py --prefix model/resnet50 --epoch 0 \\
        --input data:3,224,224 --buckets 1,4,16,64 --port 8080

    # from a PR-4 checkpoint directory, hot-reloading as training commits
    python tools/serve.py --checkpoint-dir ckpts --symbol net-symbol.json \\
        --input data:3,224,224 --watch 5

    # client
    curl -s localhost:8080/healthz
    curl -s -X POST localhost:8080/predict \\
        -H 'Content-Type: application/json' \\
        -d '{"inputs": {"data": [[...]]}}'
    curl -s localhost:8080/metrics   # Prometheus text

Pre-compiles every (replica, bucket) executable before binding the port
(zero request-path compiles; set MXNET_AOT_CACHE=1 to persist executables
so the NEXT serve process warms from disk). `--replicas N` (or auto on
TPU) replicates the model across N devices with health-gated failover —
see docs/serving.md "Failure semantics". SIGINT drains gracefully:
queued requests complete, new ones are refused.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def _parse_input(spec):
    """'name:3,224,224' -> (name, (3, 224, 224))."""
    name, _, dims = spec.partition(":")
    if not dims:
        raise argparse.ArgumentTypeError(
            f"--input wants name:d0,d1,... got {spec!r}")
    return name, tuple(int(d) for d in dims.split(","))


def _parse_type(spec):
    name, _, dt = spec.partition(":")
    if not dt:
        raise argparse.ArgumentTypeError(
            f"--input-type wants name:dtype, got {spec!r}")
    return name, dt


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    src = ap.add_argument_group("model source")
    src.add_argument("--prefix",
                     help="save_checkpoint prefix (reads "
                          "PREFIX-symbol.json + PREFIX-EPOCH.params)")
    src.add_argument("--epoch", type=int, default=0)
    src.add_argument("--symbol", help="symbol .json path")
    src.add_argument("--params", help=".params file")
    src.add_argument("--checkpoint-dir",
                     help="PR-4 checkpoint directory: initial weights come "
                          "from its latest valid commit; with --watch it "
                          "is also polled for hot reload")
    ap.add_argument("--input", action="append", type=_parse_input,
                    required=True, metavar="NAME:D0,D1,...",
                    help="per-SAMPLE input shape (no batch dim); repeat "
                         "for multi-input models")
    ap.add_argument("--input-type", action="append", type=_parse_type,
                    default=[], metavar="NAME:DTYPE",
                    help="input dtype (default float32; token ids should "
                         "be int32)")
    ap.add_argument("--buckets", default=None,
                    help="batch-size buckets (default "
                         "$MXNET_SERVING_BUCKETS)")
    ap.add_argument("--max-delay-ms", type=float, default=None)
    ap.add_argument("--queue-depth", type=int, default=None)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--replicas", type=int, default=None,
                    help="model replicas, one per device (0 = auto: all "
                         "local accelerator devices on TPU, 1 on CPU; "
                         "default $MXNET_SERVING_REPLICAS)")
    ap.add_argument("--replica-timeout-ms", type=float, default=None,
                    help="per-batch execution watchdog; a hung replica "
                         "call fails over instead of freezing dispatch "
                         "(default $MXNET_SERVING_REPLICA_TIMEOUT_MS)")
    ap.add_argument("--max-retries", type=int, default=None,
                    help="failover re-dispatches of a failed batch "
                         "(default $MXNET_SERVING_MAX_RETRIES)")
    ap.add_argument("--hedge-ms", type=float, default=None,
                    help="duplicate a slow batch to a second replica "
                         "after this delay; first result wins (default "
                         "$MXNET_SERVING_HEDGE_MS, 0 = off)")
    ap.add_argument("--max-body-bytes", type=int, default=None,
                    help="reject request bodies larger than this with "
                         "413 (default $MXNET_SERVING_MAX_BODY_BYTES)")
    ap.add_argument("--watch", type=float, default=None,
                    help="poll --checkpoint-dir every N seconds for new "
                         "checkpoints (default $MXNET_SERVING_WATCH)")
    ap.add_argument("--no-fold-bn", action="store_true",
                    help="skip the inference BatchNorm fold")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--dev-type", default="cpu",
                    choices=["cpu", "gpu", "tpu"])
    ap.add_argument("--dev-id", type=int, default=0)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.prefix:
        symbol = f"{args.prefix}-symbol.json"
        params = f"{args.prefix}-{args.epoch:04d}.params"
    elif args.checkpoint_dir:
        params = args.checkpoint_dir
        symbol = args.symbol or _latest_ckpt_symbol(args.checkpoint_dir)
    elif args.symbol and args.params:
        symbol, params = args.symbol, args.params
    else:
        ap.error("need --prefix, --checkpoint-dir, or --symbol + --params")

    from mxnet_tpu.serving import ModelServer, ServingConfig, serve_http

    config = ServingConfig(
        buckets=args.buckets, max_delay_ms=args.max_delay_ms,
        queue_depth=args.queue_depth, deadline_ms=args.deadline_ms,
        watch_dir=args.checkpoint_dir, watch_period=args.watch,
        fold_bn=not args.no_fold_bn, replicas=args.replicas,
        replica_timeout_ms=args.replica_timeout_ms,
        max_retries=args.max_retries, hedge_ms=args.hedge_ms,
        max_body_bytes=args.max_body_bytes)
    server = ModelServer(
        symbol, params, dict(args.input), config=config,
        dev_type=args.dev_type, dev_id=args.dev_id,
        input_types=dict(args.input_type) or None)
    serve_http(server, host=args.host, port=args.port)


def _latest_ckpt_symbol(ckpt_dir):
    """symbol.json inside the newest valid checkpoint commit."""
    from mxnet_tpu.checkpoint import load_latest

    loaded = load_latest(ckpt_dir)
    if loaded is None:
        sys.exit(f"no valid checkpoint under {ckpt_dir!r}")
    path = os.path.join(loaded.path, "symbol.json")
    if not os.path.exists(path):
        sys.exit(f"{loaded.path} has no symbol.json; pass --symbol")
    return path


if __name__ == "__main__":
    main()
