#!/usr/bin/env python
"""Caffe prototxt (+ .caffemodel weights) -> Symbol + params converter.

Reference: ``tools/caffe_converter/convert_symbol.py`` and
``convert_model.py`` (the reference needs the Caffe protobuf runtime;
here BOTH wire formats are parsed directly — the prototxt text-protobuf
with a hand-rolled tokenizer, and the ``.caffemodel`` binary protobuf
with a minimal varint/wire-type walker — so pretrained Caffe models
migrate with no Caffe or protoc dependency).

Supported layers: Convolution, Deconvolution, InnerProduct, Pooling
(MAX/AVE, global), ReLU, Sigmoid, TanH, LRN, Dropout, Concat, Eltwise,
BatchNorm (+ following Scale folded into gamma/beta, statistics
de-scaled by the blob scale factor), Flatten, Crop, Slice, Power,
Softmax / SoftmaxWithLoss, Accuracy (skipped), Data/Input (becomes the
data Variable). Both the modern ``layer {}`` and legacy ``layers {}``
blocks parse; in-place layers (same top as bottom) chain naturally.

Usage:
    python tools/caffe_converter.py net.prototxt [-o out-symbol.json]
    python tools/caffe_converter.py net.prototxt -w net.caffemodel \\
        -o converted          # writes converted-symbol.json + -0000.params
"""

from __future__ import annotations

import argparse
import re
import sys


# ---------------------------------------------------------------------------
# text-protobuf parsing
# ---------------------------------------------------------------------------
_TOKEN = re.compile(
    r"""
    (?P<brace_open>\{)|(?P<brace_close>\})|
    (?P<key>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<colon>:)?\s*
    (?P<value>"[^"]*"|[-+0-9.eE]+|[A-Za-z_][A-Za-z0-9_]*)?
    """,
    re.VERBOSE,
)


def parse_prototxt(text):
    """Parse text protobuf into nested dicts; repeated keys become lists."""
    # strip comments
    text = re.sub(r"#[^\n]*", "", text)
    pos = 0
    n = len(text)

    def parse_block():
        nonlocal pos
        out = {}

        def add(key, value):
            if key in out:
                if not isinstance(out[key], list):
                    out[key] = [out[key]]
                out[key].append(value)
            else:
                out[key] = value

        while pos < n:
            m = _TOKEN.match(text, pos)
            if m is None or m.end() == pos:
                pos += 1
                continue
            if m.group("brace_close"):
                pos = m.end()
                return out
            key = m.group("key")
            if key is None:
                pos = m.end()
                continue
            pos = m.end()
            # block: `key { ... }` (colon-less, value may have matched the
            # brace-opening of the block body — rewind in that case)
            rest = text[pos:].lstrip()
            if m.group("colon") is None or m.group("value") is None:
                brace = text.find("{", m.start())
                if brace != -1 and text[m.end("key"):brace].strip() in ("", ":"):
                    pos = brace + 1
                    add(key, parse_block())
                    continue
            val = m.group("value")
            if val is None:
                continue
            if val.startswith('"'):
                add(key, val[1:-1])
            elif val in ("true", "false"):  # prototxt boolean tokens
                add(key, val == "true")
            else:
                try:
                    add(key, int(val))
                except ValueError:
                    try:
                        add(key, float(val))
                    except ValueError:
                        add(key, val)  # enum token
        return out

    return parse_block()


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# layer mapping
# ---------------------------------------------------------------------------
def _hw(p, base, default=None):
    """Resolve a possibly-repeated spatial field per Caffe semantics:
    one value applies to both axes, two values are (h, w); explicit
    ``<base>_h`` / ``<base>_w`` win."""
    v = _as_list(p.get(base, default))
    if not v:
        v = [default]
    h = p.get(base + "_h", v[0])
    w = p.get(base + "_w", v[1] if len(v) > 1 else v[0])
    if h is None or w is None:
        raise ValueError(f"caffe_converter: missing required field "
                         f"{base!r} in {p}")
    return (int(h), int(w))


def _kernel(p):
    return _hw(p, "kernel_size")


def _stride(p):
    return _hw(p, "stride", 1)


def _pad(p):
    return _hw(p, "pad", 0)


def _required(p, field, layer_name):
    v = p.get(field)
    if v is None:
        raise ValueError(
            f"caffe_converter: layer {layer_name!r} is missing required "
            f"field {field!r}"
        )
    return _as_list(v)[0]


def convert_symbol(prototxt_text):
    """Return (symbol, input_name) for a Caffe network definition."""
    import mxnet_tpu as mx

    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer")) + _as_list(net.get("layers"))
    tops = {}  # blob name -> Symbol
    input_name = "data"
    for iname in _as_list(net.get("input")):
        input_name = iname
        tops[iname] = mx.sym.Variable(iname)

    def get_bottom(layer):
        bots = _as_list(layer.get("bottom"))
        syms = []
        for b in bots:
            if b not in tops:
                tops[b] = mx.sym.Variable(b)
            syms.append(tops[b])
        return syms

    last = None
    bn_tops = set()  # blobs produced by BatchNorm (Scale folds into them)
    for layer in layers:
        ltype = str(layer.get("type", "")).upper()
        name = layer.get("name", ltype.lower())
        top = _as_list(layer.get("top"))
        bottoms = get_bottom(layer)
        b0 = bottoms[0] if bottoms else None

        if ltype in ("DATA", "INPUT", "MEMORYDATA", "IMAGEDATA", "HDF5DATA"):
            # each top is its own blob (train prototxts emit data AND label)
            input_name = top[0] if top else "data"
            for t in top or ["data"]:
                tops[t] = mx.sym.Variable(t)
            last = tops[input_name]
            continue
        elif ltype == "CONVOLUTION":
            p = layer.get("convolution_param", {})
            out = mx.sym.Convolution(
                b0, num_filter=int(_required(p, "num_output", name)),
                kernel=_kernel(p), stride=_stride(p), pad=_pad(p),
                num_group=int(p.get("group", 1)),
                no_bias=not bool(p.get("bias_term", 1)), name=name,
            )
        elif ltype == "DECONVOLUTION":
            p = layer.get("convolution_param", {})
            out = mx.sym.Deconvolution(
                b0, num_filter=int(_required(p, "num_output", name)),
                kernel=_kernel(p), stride=_stride(p), pad=_pad(p),
                num_group=int(p.get("group", 1)),
                no_bias=not bool(p.get("bias_term", 1)), name=name,
            )
        elif ltype in ("INNERPRODUCT", "INNER_PRODUCT"):
            p = layer.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                b0, num_hidden=int(_required(p, "num_output", name)),
                no_bias=not bool(p.get("bias_term", 1)), name=name,
            )
        elif ltype == "POOLING":
            p = layer.get("pooling_param", {})
            pool = str(p.get("pool", "MAX")).upper()
            pmap = {"MAX": "max", "AVE": "avg", "0": "max", "1": "avg"}
            if str(pool) not in pmap:
                raise ValueError(
                    f"caffe_converter: unsupported pooling method {pool!r} "
                    f"(layer {name!r})"
                )
            ptype = pmap[str(pool)]
            if p.get("global_pooling"):
                out = mx.sym.Pooling(b0, global_pool=True, kernel=(1, 1),
                                     pool_type=ptype, name=name)
            else:
                out = mx.sym.Pooling(
                    b0, kernel=_kernel(p), stride=_stride(p), pad=_pad(p),
                    pool_type=ptype,
                    pooling_convention="full",  # caffe ceil-mode windows
                    name=name,
                )
        elif ltype == "RELU":
            out = mx.sym.Activation(b0, act_type="relu", name=name)
        elif ltype == "SIGMOID":
            out = mx.sym.Activation(b0, act_type="sigmoid", name=name)
        elif ltype == "TANH":
            out = mx.sym.Activation(b0, act_type="tanh", name=name)
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = mx.sym.LRN(
                b0, alpha=float(p.get("alpha", 1e-4)),
                beta=float(p.get("beta", 0.75)),
                knorm=float(p.get("k", 1.0)),
                nsize=int(p.get("local_size", 5)), name=name,
            )
        elif ltype == "DROPOUT":
            p = layer.get("dropout_param", {})
            out = mx.sym.Dropout(
                b0, p=float(p.get("dropout_ratio", 0.5)), name=name)
        elif ltype == "CONCAT":
            p = layer.get("concat_param", {})
            out = mx.sym.Concat(*bottoms, dim=int(p.get("axis", 1)),
                                name=name)
        elif ltype == "ELTWISE":
            p = layer.get("eltwise_param", {})
            op = str(p.get("operation", "SUM")).upper()
            if op in ("SUM", "1"):
                coeffs = [float(c) for c in _as_list(p.get("coeff"))]
                coeffs += [1.0] * (len(bottoms) - len(coeffs))
                terms = [b if c == 1.0 else b * c
                         for b, c in zip(bottoms, coeffs)]
                out = terms[0]
                for t in terms[1:]:
                    out = out + t
            elif op in ("PROD", "0"):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = out * b
            elif op in ("MAX", "2"):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = mx.sym.maximum(out, b)
            else:
                raise ValueError(
                    f"caffe_converter: unsupported eltwise op {op!r} "
                    f"(layer {name!r})"
                )
        elif ltype == "BATCHNORM":
            p = layer.get("batch_norm_param", {})
            # fix_gamma=False: the paired caffe Scale layer's learnable
            # gamma/beta live IN the BatchNorm symbol (the reference
            # converter folds them the same way, convert_symbol.py)
            out = mx.sym.BatchNorm(
                b0, eps=float(p.get("eps", 1e-5)), fix_gamma=False,
                use_global_stats=bool(p.get("use_global_stats", 0)),
                name=name,
            )
            bn_tops.update(top or [name])
        elif ltype == "SCALE":
            # folds into the preceding BatchNorm's gamma/beta; a standalone
            # Scale has no such home and silent identity would be wrong
            bot_name = _as_list(layer.get("bottom"))
            if not bot_name or bot_name[0] not in bn_tops:
                raise ValueError(
                    f"caffe_converter: standalone Scale layer {name!r} is "
                    "not supported (only BatchNorm+Scale pairs fold)"
                )
            out = b0
        elif ltype == "FLATTEN":
            out = mx.sym.Flatten(b0, name=name)
        elif ltype == "CROP":
            p = layer.get("crop_param", {})
            axis = int(p.get("axis", 2))
            if axis != 2 or len(bottoms) != 2:
                raise ValueError(
                    f"caffe_converter: Crop layer {name!r} supports only "
                    "axis=2 with a reference bottom (spatial crop-like)"
                )
            offs = [int(o) for o in _as_list(p.get("offset", 0))]
            if len(offs) == 1:
                offs = offs * 2
            out = mx.sym.Crop(b0, bottoms[1], offset=tuple(offs), name=name)
        elif ltype == "SLICE":
            p = layer.get("slice_param", {})
            axis = int(p.get("axis", p.get("slice_dim", 1)))
            points = [int(x) for x in _as_list(p.get("slice_point"))]
            ntop = len(top) if top else 2
            if points:
                # arbitrary split points -> slice_axis per segment
                bounds = [0] + points + [None]
                outs_list = [
                    mx.sym.slice_axis(b0, axis=axis, begin=bounds[i],
                                      end=bounds[i + 1],
                                      name=f"{name}_out{i}")
                    for i in range(len(bounds) - 1)
                ]
            else:
                sliced = mx.sym.SliceChannel(
                    b0, num_outputs=ntop, axis=axis, name=name)
                outs_list = [sliced[i] for i in range(ntop)]
            if len(outs_list) != len(top or []):
                raise ValueError(
                    f"caffe_converter: Slice layer {name!r} produces "
                    f"{len(outs_list)} outputs for {len(top or [])} tops"
                )
            for t, o in zip(top, outs_list):
                tops[t] = o
            last = outs_list[-1]
            continue
        elif ltype == "POWER":
            p = layer.get("power_param", {})
            power = float(p.get("power", 1.0))
            scale = float(p.get("scale", 1.0))
            shift = float(p.get("shift", 0.0))
            out = b0
            if scale != 1.0:
                out = out * scale
            if shift != 0.0:
                out = out + shift
            if power != 1.0:
                out = out ** power
        elif ltype in ("SOFTMAX", "SOFTMAXWITHLOSS", "SOFTMAX_LOSS"):
            if len(bottoms) > 1:
                out = mx.sym.SoftmaxOutput(b0, bottoms[1], name=name)
            else:
                out = mx.sym.SoftmaxOutput(b0, name=name)
        elif ltype == "ACCURACY":
            continue  # evaluation-only layer
        else:
            raise ValueError(
                f"caffe_converter: unsupported layer type {ltype!r} "
                f"(layer {name!r})"
            )
        for t in top or [name]:
            tops[t] = out
        last = out
    if last is None:
        raise ValueError("no layers found in prototxt")
    return last, input_name


# ---------------------------------------------------------------------------
# .caffemodel binary protobuf reader (no protoc / caffe dependency)
# ---------------------------------------------------------------------------
def _uvarint(buf, pos):
    """Decode one unsigned varint; returns (value, new_pos). Raises on a
    truncated buffer instead of reading garbage."""
    v = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise ValueError("caffe_converter: truncated protobuf (varint "
                             "runs past end of buffer)")
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return v, pos


def _pb_walk(buf):
    """Yield (field_number, wire_type, value) over one protobuf message.

    value is an int for varint(0)/fixed(1,5) fields and a memoryview for
    length-delimited(2) fields. Groups (3,4) are rejected — Caffe never
    emits them."""
    import struct as _struct

    pos, n = 0, len(buf)
    mv = memoryview(buf)
    while pos < n:
        tag, pos = _uvarint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _uvarint(buf, pos)
            yield field, wt, v
        elif wt == 2:
            ln, pos = _uvarint(buf, pos)
            if pos + ln > n:
                raise ValueError(
                    f"caffe_converter: truncated protobuf (field {field} "
                    f"declares {ln} bytes, {n - pos} remain)")
            yield field, wt, mv[pos:pos + ln]
            pos += ln
        elif wt == 5:
            yield field, wt, _struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wt == 1:
            yield field, wt, _struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        else:
            raise ValueError(f"caffe_converter: unsupported protobuf wire "
                             f"type {wt} (field {field})")


def _parse_blob(buf):
    """BlobProto -> float32 ndarray (caffe.proto: shape=7{dim=1}, packed
    float data=5, packed double double_data=8, legacy num/c/h/w=1..4)."""
    import struct as _struct

    import numpy as np

    dims = []
    legacy = [None] * 4  # num, channels, height, width
    data = []
    for field, wt, v in _pb_walk(buf):
        if field == 7 and wt == 2:  # BlobShape
            for f2, w2, v2 in _pb_walk(v):
                if f2 == 1 and w2 == 2:  # packed int64 dims
                    b2 = bytes(v2)
                    p2 = 0
                    while p2 < len(b2):
                        d, p2 = _uvarint(b2, p2)
                        dims.append(d)
                elif f2 == 1 and w2 == 0:
                    dims.append(v2)
        elif field == 5:  # float data
            if wt == 2:
                data.append(np.frombuffer(v, dtype="<f4"))
            else:  # unpacked fixed32
                data.append(np.asarray(
                    [_struct.unpack("<f", _struct.pack("<I", v))[0]],
                    dtype=np.float32))
        elif field == 8 and wt == 2:  # packed double data
            data.append(np.frombuffer(v, dtype="<f8").astype(np.float32))
        elif field in (1, 2, 3, 4) and wt == 0:
            legacy[field - 1] = v
    arr = (np.concatenate(data) if data
           else np.zeros(0, np.float32)).astype(np.float32)
    if not dims and any(x is not None for x in legacy):
        dims = [x for x in legacy if x is not None]
    if dims and int(np.prod(dims)) == arr.size:
        arr = arr.reshape(dims)
    return arr


def read_caffemodel(data):
    """Parse .caffemodel bytes -> ordered [(layer_name, [blobs])].

    Handles both the modern ``LayerParameter layer = 100`` (name=1,
    blobs=7) and the legacy ``V1LayerParameter layers = 2`` (name=4,
    blobs=6) encodings of NetParameter."""
    # NetParameter field -> that layer encoding's (name, blobs) fields
    encodings = {100: (1, 7), 2: (4, 6)}
    out = []
    for field, wt, v in _pb_walk(data):
        if field in encodings and wt == 2:
            name_field, blob_field = encodings[field]
            name, blobs = "", []
            for f2, w2, v2 in _pb_walk(v):
                if f2 == name_field and w2 == 2:
                    name = bytes(v2).decode("utf-8")
                elif f2 == blob_field and w2 == 2:
                    blobs.append(_parse_blob(v2))
            out.append((name, blobs))
    return out


def convert_model(prototxt_text, caffemodel_bytes):
    """Convert a trained Caffe model: (symbol, arg_params, aux_params,
    input_name). The reference analogue is convert_model.py:47-137 —
    conv/fc weights map by layer name, an InnerProduct weight reshapes to
    the symbol's inferred 2-d shape, BatchNorm statistics de-scale by the
    running scale factor, and a following Scale layer's gamma/beta land
    in the folded BatchNorm symbol's arguments."""
    import numpy as np

    import mxnet_tpu as mx

    sym, input_name = convert_symbol(prototxt_text)
    weights = dict(read_caffemodel(caffemodel_bytes))

    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer")) + _as_list(net.get("layers"))
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params, aux_params = {}, {}
    blob_owner = {}  # top blob name -> producing BN layer name

    def put_arg(pname, arr):
        if pname in arg_names:
            arg_params[pname] = mx.nd.array(np.asarray(arr, np.float32))

    for layer in layers:
        ltype = str(layer.get("type", "")).upper()
        name = layer.get("name", "")
        blobs = weights.get(name)
        if ltype == "BATCHNORM":
            for t in _as_list(layer.get("top")) or [name]:
                blob_owner[t] = name
        if not blobs:
            continue
        if ltype in ("CONVOLUTION", "DECONVOLUTION", "INNERPRODUCT",
                     "INNER_PRODUCT"):
            w = blobs[0]
            if ltype in ("INNERPRODUCT", "INNER_PRODUCT") and w.ndim > 2:
                # legacy blobs carry 4-d (1,1,N,D) dims; the matrix is the
                # trailing two
                w = w.reshape(w.shape[-2], w.shape[-1])
            put_arg(f"{name}_weight", w)
            if len(blobs) > 1:
                put_arg(f"{name}_bias", blobs[1].ravel())
        elif ltype == "BATCHNORM":
            sf = float(blobs[2].ravel()[0]) if len(blobs) > 2 and \
                blobs[2].size else 1.0
            sf = 1.0 / sf if sf != 0 else 0.0
            if f"{name}_moving_mean" in aux_names:
                aux_params[f"{name}_moving_mean"] = mx.nd.array(
                    blobs[0].ravel() * sf)
                aux_params[f"{name}_moving_var"] = mx.nd.array(
                    blobs[1].ravel() * sf)
            # gamma/beta default to identity unless a Scale layer follows
            put_arg(f"{name}_gamma", np.ones_like(blobs[0].ravel()))
            put_arg(f"{name}_beta", np.zeros_like(blobs[0].ravel()))
        elif ltype == "SCALE":
            bot = _as_list(layer.get("bottom"))
            bn = blob_owner.get(bot[0]) if bot else None
            if bn is None:
                continue  # convert_symbol already rejected standalone Scale
            put_arg(f"{bn}_gamma", blobs[0].ravel())
            if len(blobs) > 1:
                put_arg(f"{bn}_beta", blobs[1].ravel())
    return sym, arg_params, aux_params, input_name


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("prototxt")
    ap.add_argument("-w", "--weights", default=None,
                    help=".caffemodel to convert alongside the symbol")
    ap.add_argument("-o", "--output", default=None,
                    help="write symbol JSON here (default: stdout); with "
                         "-w, treated as a checkpoint prefix")
    args = ap.parse_args()
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    with open(args.prototxt) as f:
        text = f.read()
    if args.weights:
        import mxnet_tpu as mx

        with open(args.weights, "rb") as f:
            sym, arg_params, aux_params, _ = convert_model(text, f.read())
        prefix = args.output or os.path.splitext(args.prototxt)[0]
        mx.model.save_checkpoint(
            prefix, 0, sym, arg_params, aux_params)
        print(f"wrote {prefix}-symbol.json and {prefix}-0000.params")
        return
    symbol, _ = convert_symbol(text)
    js = symbol.tojson()
    if args.output:
        with open(args.output, "w") as f:
            f.write(js)
    else:
        print(js)


if __name__ == "__main__":
    main()
