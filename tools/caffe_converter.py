#!/usr/bin/env python
"""Caffe prototxt -> Symbol converter.

Reference: ``tools/caffe_converter/convert_symbol.py`` (parses a Caffe
network definition and emits the equivalent mx.symbol graph; its sibling
``convert_model.py`` additionally converts ``.caffemodel`` weights, which
requires the Caffe protobuf runtime and is out of scope here — weights
import via the standard ``.params`` path instead).

The prototxt text-protobuf format is parsed directly (no protobuf
dependency): both the modern ``layer {}`` and legacy ``layers {}`` blocks,
string and enum layer types. Supported layers: Convolution, InnerProduct,
Pooling (MAX/AVE, global), ReLU, LRN, Dropout, Concat, Eltwise (SUM),
BatchNorm (+ following Scale folded in), Flatten, Softmax /
SoftmaxWithLoss, Accuracy (skipped), Data/Input (becomes the data
Variable). In-place layers (same top as bottom) chain naturally.

Usage:
    python tools/caffe_converter.py net.prototxt [-o out-symbol.json]
"""

from __future__ import annotations

import argparse
import re
import sys


# ---------------------------------------------------------------------------
# text-protobuf parsing
# ---------------------------------------------------------------------------
_TOKEN = re.compile(
    r"""
    (?P<brace_open>\{)|(?P<brace_close>\})|
    (?P<key>[A-Za-z_][A-Za-z0-9_]*)\s*(?P<colon>:)?\s*
    (?P<value>"[^"]*"|[-+0-9.eE]+|[A-Za-z_][A-Za-z0-9_]*)?
    """,
    re.VERBOSE,
)


def parse_prototxt(text):
    """Parse text protobuf into nested dicts; repeated keys become lists."""
    # strip comments
    text = re.sub(r"#[^\n]*", "", text)
    pos = 0
    n = len(text)

    def parse_block():
        nonlocal pos
        out = {}

        def add(key, value):
            if key in out:
                if not isinstance(out[key], list):
                    out[key] = [out[key]]
                out[key].append(value)
            else:
                out[key] = value

        while pos < n:
            m = _TOKEN.match(text, pos)
            if m is None or m.end() == pos:
                pos += 1
                continue
            if m.group("brace_close"):
                pos = m.end()
                return out
            key = m.group("key")
            if key is None:
                pos = m.end()
                continue
            pos = m.end()
            # block: `key { ... }` (colon-less, value may have matched the
            # brace-opening of the block body — rewind in that case)
            rest = text[pos:].lstrip()
            if m.group("colon") is None or m.group("value") is None:
                brace = text.find("{", m.start())
                if brace != -1 and text[m.end("key"):brace].strip() in ("", ":"):
                    pos = brace + 1
                    add(key, parse_block())
                    continue
            val = m.group("value")
            if val is None:
                continue
            if val.startswith('"'):
                add(key, val[1:-1])
            elif val in ("true", "false"):  # prototxt boolean tokens
                add(key, val == "true")
            else:
                try:
                    add(key, int(val))
                except ValueError:
                    try:
                        add(key, float(val))
                    except ValueError:
                        add(key, val)  # enum token
        return out

    return parse_block()


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# ---------------------------------------------------------------------------
# layer mapping
# ---------------------------------------------------------------------------
def _kernel(p):
    k = p.get("kernel_size", p.get("kernel_h"))
    kh = p.get("kernel_h", k)
    kw = p.get("kernel_w", k)
    return (int(kh), int(kw))


def _stride(p):
    s = p.get("stride", 1)
    return (int(p.get("stride_h", s)), int(p.get("stride_w", s)))


def _pad(p):
    d = p.get("pad", 0)
    return (int(p.get("pad_h", d)), int(p.get("pad_w", d)))


def convert_symbol(prototxt_text):
    """Return (symbol, input_name) for a Caffe network definition."""
    import mxnet_tpu as mx

    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer")) + _as_list(net.get("layers"))
    tops = {}  # blob name -> Symbol
    input_name = "data"
    for iname in _as_list(net.get("input")):
        input_name = iname
        tops[iname] = mx.sym.Variable(iname)

    def get_bottom(layer):
        bots = _as_list(layer.get("bottom"))
        syms = []
        for b in bots:
            if b not in tops:
                tops[b] = mx.sym.Variable(b)
            syms.append(tops[b])
        return syms

    last = None
    bn_tops = set()  # blobs produced by BatchNorm (Scale folds into them)
    for layer in layers:
        ltype = str(layer.get("type", "")).upper()
        name = layer.get("name", ltype.lower())
        top = _as_list(layer.get("top"))
        bottoms = get_bottom(layer)
        b0 = bottoms[0] if bottoms else None

        if ltype in ("DATA", "INPUT", "MEMORYDATA", "IMAGEDATA", "HDF5DATA"):
            # each top is its own blob (train prototxts emit data AND label)
            input_name = top[0] if top else "data"
            for t in top or ["data"]:
                tops[t] = mx.sym.Variable(t)
            last = tops[input_name]
            continue
        elif ltype == "CONVOLUTION":
            p = layer.get("convolution_param", {})
            out = mx.sym.Convolution(
                b0, num_filter=int(p["num_output"]), kernel=_kernel(p),
                stride=_stride(p), pad=_pad(p),
                num_group=int(p.get("group", 1)),
                no_bias=not bool(p.get("bias_term", 1)), name=name,
            )
        elif ltype in ("INNERPRODUCT", "INNER_PRODUCT"):
            p = layer.get("inner_product_param", {})
            out = mx.sym.FullyConnected(
                b0, num_hidden=int(p["num_output"]),
                no_bias=not bool(p.get("bias_term", 1)), name=name,
            )
        elif ltype == "POOLING":
            p = layer.get("pooling_param", {})
            pool = str(p.get("pool", "MAX")).upper()
            pmap = {"MAX": "max", "AVE": "avg", "0": "max", "1": "avg"}
            if str(pool) not in pmap:
                raise ValueError(
                    f"caffe_converter: unsupported pooling method {pool!r} "
                    f"(layer {name!r})"
                )
            ptype = pmap[str(pool)]
            if p.get("global_pooling"):
                out = mx.sym.Pooling(b0, global_pool=True, kernel=(1, 1),
                                     pool_type=ptype, name=name)
            else:
                out = mx.sym.Pooling(
                    b0, kernel=_kernel(p), stride=_stride(p), pad=_pad(p),
                    pool_type=ptype,
                    pooling_convention="full",  # caffe ceil-mode windows
                    name=name,
                )
        elif ltype == "RELU":
            out = mx.sym.Activation(b0, act_type="relu", name=name)
        elif ltype == "SIGMOID":
            out = mx.sym.Activation(b0, act_type="sigmoid", name=name)
        elif ltype == "TANH":
            out = mx.sym.Activation(b0, act_type="tanh", name=name)
        elif ltype == "LRN":
            p = layer.get("lrn_param", {})
            out = mx.sym.LRN(
                b0, alpha=float(p.get("alpha", 1e-4)),
                beta=float(p.get("beta", 0.75)),
                knorm=float(p.get("k", 1.0)),
                nsize=int(p.get("local_size", 5)), name=name,
            )
        elif ltype == "DROPOUT":
            p = layer.get("dropout_param", {})
            out = mx.sym.Dropout(
                b0, p=float(p.get("dropout_ratio", 0.5)), name=name)
        elif ltype == "CONCAT":
            p = layer.get("concat_param", {})
            out = mx.sym.Concat(*bottoms, dim=int(p.get("axis", 1)),
                                name=name)
        elif ltype == "ELTWISE":
            p = layer.get("eltwise_param", {})
            op = str(p.get("operation", "SUM")).upper()
            if op in ("SUM", "1"):
                coeffs = [float(c) for c in _as_list(p.get("coeff"))]
                coeffs += [1.0] * (len(bottoms) - len(coeffs))
                terms = [b if c == 1.0 else b * c
                         for b, c in zip(bottoms, coeffs)]
                out = terms[0]
                for t in terms[1:]:
                    out = out + t
            elif op in ("PROD", "0"):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = out * b
            elif op in ("MAX", "2"):
                out = bottoms[0]
                for b in bottoms[1:]:
                    out = mx.sym.maximum(out, b)
            else:
                raise ValueError(
                    f"caffe_converter: unsupported eltwise op {op!r} "
                    f"(layer {name!r})"
                )
        elif ltype == "BATCHNORM":
            p = layer.get("batch_norm_param", {})
            # fix_gamma=False: the paired caffe Scale layer's learnable
            # gamma/beta live IN the BatchNorm symbol (the reference
            # converter folds them the same way, convert_symbol.py)
            out = mx.sym.BatchNorm(
                b0, eps=float(p.get("eps", 1e-5)), fix_gamma=False,
                use_global_stats=bool(p.get("use_global_stats", 0)),
                name=name,
            )
            bn_tops.update(top or [name])
        elif ltype == "SCALE":
            # folds into the preceding BatchNorm's gamma/beta; a standalone
            # Scale has no such home and silent identity would be wrong
            bot_name = _as_list(layer.get("bottom"))
            if not bot_name or bot_name[0] not in bn_tops:
                raise ValueError(
                    f"caffe_converter: standalone Scale layer {name!r} is "
                    "not supported (only BatchNorm+Scale pairs fold)"
                )
            out = b0
        elif ltype == "FLATTEN":
            out = mx.sym.Flatten(b0, name=name)
        elif ltype in ("SOFTMAX", "SOFTMAXWITHLOSS", "SOFTMAX_LOSS"):
            if len(bottoms) > 1:
                out = mx.sym.SoftmaxOutput(b0, bottoms[1], name=name)
            else:
                out = mx.sym.SoftmaxOutput(b0, name=name)
        elif ltype == "ACCURACY":
            continue  # evaluation-only layer
        else:
            raise ValueError(
                f"caffe_converter: unsupported layer type {ltype!r} "
                f"(layer {name!r})"
            )
        for t in top or [name]:
            tops[t] = out
        last = out
    if last is None:
        raise ValueError("no layers found in prototxt")
    return last, input_name


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("prototxt")
    ap.add_argument("-o", "--output", default=None,
                    help="write symbol JSON here (default: stdout)")
    args = ap.parse_args()
    import os

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    with open(args.prototxt) as f:
        symbol, _ = convert_symbol(f.read())
    js = symbol.tojson()
    if args.output:
        with open(args.output, "w") as f:
            f.write(js)
    else:
        print(js)


if __name__ == "__main__":
    main()
