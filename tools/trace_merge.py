#!/usr/bin/env python
"""Merge a host span trace and a device trace into one Chrome trace JSON.

The host file comes from ``mxnet_tpu.telemetry.dump_trace`` (spans recorded
under ``MXNET_TELEMETRY=1``); the device file from
``mxnet_tpu.profiler.dump_profile`` (or a raw ``*.trace.json.gz`` out of the
jax profiler logdir — gzip is handled transparently). The output loads in
chrome://tracing or https://ui.perfetto.dev as ONE timeline: host rows are
keyed by their own pid/tid and sit alongside the device rows.

Standalone on purpose (stdlib only): merging two JSON files must not require
importing the framework — usable on a laptop against traces scp'd off a TPU
host.

Usage:
    python tools/trace_merge.py host_spans.json device_trace.json -o merged.json
    python tools/trace_merge.py host_spans.json  # host-only passthrough
"""

import argparse
import gzip
import json
import sys


def load_trace(path):
    """A chrome trace as a dict with a 'traceEvents' list (bare event-array
    files are legal chrome JSON and get wrapped)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, list):
        return {"traceEvents": data}
    if not isinstance(data, dict):
        raise SystemExit(f"{path}: not a chrome trace (got {type(data).__name__})")
    return data


def merge(host_path, device_path, out_path):
    """Concatenate event lists; device-side metadata keys win (they carry
    the profiler's clock/domain info)."""
    merged = {"displayTimeUnit": "ms"}
    events = []
    if device_path:
        dev = load_trace(device_path)
        merged.update(dev)
        events.extend(dev.get("traceEvents") or [])
    host = load_trace(host_path)
    events.extend(host.get("traceEvents") or [])
    merged["traceEvents"] = events
    with open(out_path, "w") as f:
        json.dump(merged, f)
    return len(events)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("host", help="host span trace JSON (telemetry.dump_trace)")
    ap.add_argument("device", nargs="?", default=None,
                    help="device trace JSON[.gz] (profiler.dump_profile)")
    ap.add_argument("-o", "--out", default="merged_trace.json",
                    help="output path (default: merged_trace.json)")
    args = ap.parse_args(argv)
    n = merge(args.host, args.device, args.out)
    print(f"{args.out}: {n} events", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
