"""Benchmark: ResNet-50 ImageNet training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's published ResNet-50 train throughput on its best
single GPU (P100, 181.53 img/s @ bs32, docs/how_to/perf.md:179-188 — see
BASELINE.md). Methodology mirrors ``train_imagenet.py --benchmark 1``:
synthetic data, train-mode forward+backward+update, steady-state timing.

Steps are dispatched through ``Module.train_window`` (K fused steps per
XLA program, default K=20 on TPU; BENCH_FUSED_STEPS=1 restores per-step
dispatch) — the framework's intended steady-state training loop. Every
window iteration is a full fwd+bwd+update on the synthetic batch, exactly
like the reference's benchmark loop; the window only removes per-step
host dispatch, which on a tunneled chip costs a serialized ~3 ms round
trip that the reference's threaded engine would likewise pipeline away.

``BENCH_MODE=fit`` instead times the REAL training loop: ``Module.fit``
over an ``NDArrayIter`` with an ``Accuracy`` metric — device prefetch
staging each batch and device-resident metric accumulation keep the
epoch free of per-batch host syncs, so the fit loop must reach the
``train_window`` steady-state rate (the async-pipeline acceptance bar).
Epochs are timed at their epoch_end_callback boundaries; the first epoch
(compile) is discarded and the median of the rest is reported. On TPU,
fit mode defaults ``MXNET_TRAIN_WINDOW=auto`` so the loop runs the
framework's intended steady state: adaptive fused windows dispatched as
a PIPELINE (``MXNET_DISPATCH_DEPTH`` windows in flight, lazy boundary
publication); the JSON tail reports the operative ``train_window_k``,
``dispatch_depth``, ``peak_windows_in_flight`` and the steady-state
``dispatch_span_share`` (fit.dispatch's share of the host loop) so the
trajectory records why the number moved. ``BENCH_SWEEP=1`` grid-sweeps
K (``BENCH_SWEEP_K``) x depth (``BENCH_SWEEP_DEPTH``) with short fit
runs first, adopts the winner for the headline measurement, and embeds
the per-combo rates under ``"sweep"``.

Both window paths dispatch with ``publish_grads=False``: nothing in a
bench loop reads per-window gradients, so the boundary's f32 gradient
publication is dead-coded out of the fused program (the same lazy-
boundary contract the pipelined fit loop uses).

The result JSON always embeds a telemetry snapshot (``"telemetry"`` key)
so BENCH_* files carry the bound — data- vs dispatch- vs sync-bound — of
the measured run. With ``MXNET_TELEMETRY=1`` in fit mode, the run
additionally captures host spans + the jax device trace and writes one
merged Perfetto-loadable timeline (``BENCH_TRACE_OUT``, default
bench_trace.json) plus the snapshot JSON/Prometheus pair
(``BENCH_TELEMETRY_OUT``, default bench_telemetry.json).

Compile-cost trajectory: both modes report ``cold_compile_s`` (the first
epoch / warmup duration — where XLA compilation lives) and
``warm_start_s`` (a FRESH module bound and stepped once after the timed
run), so the AOT executable cache win (``MXNET_AOT_CACHE=1`` — warm
start deserializes instead of recompiling) is tracked by the bench
trajectory, not just asserted in tests. ``BENCH_WARM_START=0`` skips the
extra measurement. ``MXNET_TRAIN_WINDOW=auto`` in fit mode engages the
adaptive window scheduler; the chosen K is reported as
``train_window_k``.

Robustness cost: train mode re-times the loop with the non-finite-
gradient sentinel on (``MXNET_NONFINITE_GUARD=skip``) and reports
``nonfinite_guard_overhead`` = 1 - guarded/unguarded img/s (expected
<2%: one all-finite reduce fused into the donated step, no host sync).
``BENCH_GUARD=0`` skips it.

``BENCH_MODE=serve`` times the INFERENCE serving path:
``serving.ModelServer`` (dynamic batcher over per-bucket pre-compiled
predictors, replicated across ``BENCH_SERVE_REPLICAS`` devices — 0 =
auto) under ``BENCH_SERVE_CLIENTS`` synthetic concurrent client
threads, reporting ``serving_throughput`` (img/s), request p50/p99
latency (from the server's log-bucket histogram),
``sequential_img_per_sec`` — the same model driven one request at a time
through the batch-1 predictor — plus ``replicas`` and
``per_replica_batches`` (the replication scaling evidence). With > 1
replica it also measures ``single_replica_img_per_sec`` under the same
concurrent load (``replica_scaling`` = the replication win;
``BENCH_SERVE_SCALING=0`` skips). The batcher must beat sequential
batch-1 (the smoke pin in tests/test_bench_smoke.py), and the embedded
telemetry snapshot must show ``executor.jit_compile == 0`` — the warmed
request path never compiles.

``BENCH_SERVE_SHARDED=1`` adds the MESH-NATIVE serving legs over a
tp-annotated MLP: one ``sharded`` sub-record per ``BENCH_SERVE_MESH_LEGS``
spec (default ``tp2,pp2,dp-tp2`` — single tp2 group, single GPipe pp2
group, and every tp2 group as a dp replica) with per-leg img/s, p99 and
``request_path_compiles`` (pinned 0), plus the ``tp2_scaling_curve``
(throughput at 1/2/4 two-device groups; ``group_scaling_4x`` is the
ratio the trajectory tracks). Needs >= 8 devices — real chips or
``--xla_force_host_platform_device_count=8``.

``BENCH_CHAOS=1`` adds the availability-under-chaos leg: one replica is
killed (env fault injection) under concurrent traffic, then revived;
the JSON tail reports ``availability`` (completed/total across
pre/fault/recover phases — pinned >= 0.99 in the cpu smoke),
``p99_during_fault_ms``, the failover count, and the killed replica's
final state (probe-recovered or still open).

``BENCH_MODE=suite`` emits the WHOLE-ZOO scoreboard: every BASELINE
workload — MLP, LeNet, ResNet-50, bucketed LSTM-PTB, SSD-VGG16, DCGAN —
through the modern stack (fused K-step train windows, ``BENCH_SUITE_K``;
pipelined dispatch, ``BENCH_SUITE_DEPTH`` windows in flight), one
sub-record per workload with train+infer samples/s, analytic
``gflops_per_sample_fwd`` (models.recipe.estimate_flops; MFU on TPU
bf16), dtype, window K, dispatch depth and ``steady_compiles`` — the
compile count over the timed region, pinned 0 by the cpu smoke. The
DCGAN leg also times the reference imperative loop
(``legacy_train_samples_per_sec``) so the fused-step win is a recorded
number, not a claim. ``BENCH_SUITE_WORKLOADS`` subsets by name; the
headline value is the geomean train rate. See docs/benchmarks.md.

``BENCH_MODE=score`` sweeps forward-only scoring over the 14 zoo symbols
of the published perf table, sharing the symbol list
(``models.SCORE_SYMBOLS``) and the scoring loop with
``examples/benchmark_score.py``. ``BENCH_SCORE_NETS`` subsets,
``BENCH_SCORE_BATCH`` sizes; per-net records carry samples/s + analytic
GFLOPs (+ MFU on TPU bf16); the headline is the geomean img/s.

``BENCH_MODE=ckpt`` times the CHECKPOINT save pause on the training
thread: two identical fit passes with per-epoch + mid-epoch v2 sharded
saves — synchronous, then ``MXNET_CKPT_ASYNC``-style async — reporting
per-save ``snapshot_us`` / ``write_us`` / ``write_async_us``, the
resulting ``pause_us`` each mode charges the training loop, and
``async_vs_sync_pause`` (the bounded-stall win; ``BENCH_CKPT_EPOCHS``
sizes the pass).

``BENCH_MODE=io`` measures the INPUT PLANE alone: ImageRecordIter
decode+augment img/s over a generated synthetic-JPEG ``.rec``, serial
baseline vs the supervised decode pool at each ``BENCH_IO_WORKERS``
count. The record carries the full ``scaling`` curve, the gated
``pool_speedup`` ratio, and the ``io.plane.*`` telemetry snapshot.
``BENCH_FIT_DATA=recordio`` makes the fit mode train ResNet from a
generated RecordIO file end-to-end (metric suffix ``_recordio``) — the
number that proves the plane feeds the chip at device rate. See
docs/io.md.
"""
# graftlint: allow=env-registry(bench drives the framework's declared MXNET_* knobs and chaos injection by writing/restoring os.environ by design — the sweep and chaos legs ARE env manipulation)

import json
import os
import sys
import time

import numpy as np

# reference P100 ResNet-50 train img/s @bs32 (BASELINE.md)
BASELINE_IMG_PER_SEC = 181.53


def _build_module(mx, models, batch_size, image, dtype, num_layers, on_tpu):
    sym = models.resnet(
        num_classes=1000, num_layers=num_layers,
        image_shape=",".join(map(str, image)),
    )
    ctx = mx.gpu() if on_tpu else mx.cpu()
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(
        data_shapes=[mx.io.DataDesc("data", (batch_size,) + image, dtype)],
        label_shapes=[mx.io.DataDesc("softmax_label", (batch_size,))],
    )
    mod.init_params(initializer=mx.init.Xavier(rnd_type="gaussian",
                                               factor_type="in", magnitude=2))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01, "momentum": 0.9})
    return mod


def _write_bench_rec(mx, path, n, image, seed=0):
    """Synthetic-JPEG RecordIO fixture for the io/recordio bench legs:
    ``n`` random images a shade larger than ``image`` (so rand_crop has
    room), labels = record id % 1000."""
    from mxnet_tpu import recordio

    rng = np.random.RandomState(seed)
    side = image[1] + max(8, image[1] // 8)
    rec = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (side, side, 3), np.uint8)
        rec.write(recordio.pack_img((0, float(i % 1000), i, 0), img))
    rec.close()
    return path


def _recordio_fit_iter(mx, batch_size, image, iters, windows):
    """BENCH_FIT_DATA=recordio: an ImageRecordIter over a generated .rec
    holding exactly the samples one epoch consumes — the leg that proves
    the decode plane feeds the chip at the synthetic-data rate."""
    import tempfile

    td = tempfile.mkdtemp(prefix="bench_recordio_")
    path = _write_bench_rec(mx, os.path.join(td, "train.rec"),
                            batch_size * iters, image)
    workers = int(os.environ.get("BENCH_IO_WORKERS_FIT", 4))
    return mx.io.ImageRecordIter(
        path_imgrec=path, data_shape=image, batch_size=batch_size,
        rand_crop=True, rand_mirror=True, shuffle=True, seed=0,
        preprocess_threads=workers)


def _run_fit_mode(mx, mod, batch_size, image, dtype, iters, windows,
                  fit_data="synthetic"):
    """Time Module.fit epochs over a real data iterator (+Accuracy
    metric): an in-memory NDArrayIter by default, or the RecordIO decode
    plane when ``fit_data == "recordio"``."""
    if fit_data == "recordio":
        train = _recordio_fit_iter(mx, batch_size, image, iters, windows)
    else:
        rng = np.random.RandomState(0)
        n = batch_size * iters
        # cast to the BOUND dtype up front (bfloat16 on TPU): the executor
        # was compiled for it, and staging f32 would double the H2D bytes
        data = rng.uniform(-1, 1, (n,) + image).astype(mx.base.np_dtype(dtype))
        label = rng.randint(0, 1000, (n,)).astype(np.float32)
        train = mx.io.NDArrayIter(data, label, batch_size=batch_size,
                                  last_batch_handle="discard")
    marks = []

    def epoch_mark(epoch, sym=None, arg=None, aux=None):
        marks.append(time.time())
        if epoch == 0:
            # the first (compile) epoch is discarded from the timing; drop
            # its telemetry too so the embedded snapshot reflects the
            # steady state (compile-epoch dispatch times would dwarf the
            # per-batch phase numbers the bound verdict reads)
            mx.telemetry.reset()

    metric = mx.metric.Accuracy()
    t0 = time.time()
    mod.fit(train, eval_metric=metric, num_epoch=windows + 1,
            optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
            epoch_end_callback=epoch_mark)
    durations = np.diff([t0] + marks)
    steady = durations[1:] if len(durations) > 1 else durations
    rates = batch_size * iters / steady
    rate = float(np.median(rates))
    spread = float((rates.max() - rates.min()) / rate) if len(rates) > 1 else 0.0
    # the discarded first epoch is where XLA compilation lives — report it
    # so the compile-cache win shows up in the bench trajectory
    cold_compile_s = float(durations[0]) if len(durations) > 1 else 0.0
    return rate, spread, cold_compile_s


def _time_warm_start(mx, models, batch_size, image, dtype, num_layers,
                     on_tpu, fused=1):
    """Bind a FRESH module and run one dispatch (a `fused`-step window when
    fused>1, matching the timed loop's program shape): with the ambient
    MXNET_AOT_CACHE state this measures cache-deserialize vs recompile."""
    mod = _build_module(mx, models, batch_size, image, dtype, num_layers,
                        on_tpu)
    rng = np.random.RandomState(1)
    data = mx.nd.array(
        rng.uniform(-1, 1, (batch_size,) + image).astype(np.float32),
        dtype=dtype)
    label = mx.nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32))
    batch = mx.io.DataBatch(data=[data], label=[label])
    tic = time.time()
    if fused > 1:
        # publish_grads=False matches the timed loop's program shape, so
        # the AOT cache entry the loop warmed serves this fresh module
        mod.train_window(batch, fused, publish_grads=False)
    else:
        mod.forward_backward(batch)
        mod.update()
    np.asarray(mod.get_outputs()[0]._data[0, :1])
    return round(time.time() - tic, 3)


def _maybe_mesh(record, mx):
    """Attach the operative GraftMesh layout (MXNET_MESH or an installed
    mesh) so a bench record is attributable to its parallelism config."""
    gm = mx.parallel.current_graft()
    if gm is not None:
        record["mesh"] = gm.spec


# bf16 peak per device kind; unknown kinds omit MFU rather than report
# against the wrong denominator
_PEAKS_TFLOPS_BF16 = {"TPU v5 lite": 197, "TPU v5e": 197,
                      "TPU v4": 275, "TPU v5p": 459,
                      "TPU v6 lite": 918, "TPU v6e": 918}


def _peak_tflops(jax):
    kind = getattr(jax.devices()[0], "device_kind", "")
    return next((v for k, v in _PEAKS_TFLOPS_BF16.items() if k in kind), None)


def _fwd_flops(models, sym, **shapes):
    """Analytic forward FLOPs/sample via models.recipe.estimate_flops
    (MAC convention: ResNet-50@224 ≈ 4.1e9). None when the symbol holds an
    op the estimator can't shape-infer — MFU is then omitted, not wrong."""
    try:
        return float(models.recipe.estimate_flops(sym, **shapes))
    except Exception:
        return None


def _maybe_mfu(record, samples_per_sec, jax, on_tpu, dtype, flops_per_sample,
               key="mfu"):
    """Attach model-FLOPs-utilization when the analytic per-sample FLOPs
    and the device-kind bf16 peak are both known. ``flops_per_sample`` is
    the full cost of what the rate counts — callers pass 3x the forward
    estimate for train rates (fwd + input-grad + weight-grad)."""
    if not (on_tpu and dtype == "bfloat16" and flops_per_sample):
        return
    peak = _peak_tflops(jax)
    if peak:
        record[key] = round(
            samples_per_sec * flops_per_sample / (peak * 1e12), 3)


def _stamp_device_recipe(record, mx, models, on_tpu, dtype):
    """Stamp the resolved conv-stack device layout (MXNET_CONV_LAYOUT,
    ops/layout.py) and the precision recipe on a headline record, so a
    rate move in the trajectory is attributable to the device-side config
    that caused it."""
    record["layout"] = models.recipe.conv_layout(
        mx.gpu() if on_tpu else mx.cpu())
    record["recipe"] = models.recipe.recipe_name(dtype)


def _kernel_attribution(mx, mod, batch, k=2):
    """Top-10 per-kernel device-time table for one steady-state train
    window of ``mod``: traced AFTER the timed region (attribution never
    pollutes the measurement) with the jax device profiler and aggregated
    by telemetry.kernel_table. Returns [] when the profiler is
    unavailable; BENCH_KERNELS=0 skips the extra window entirely. The
    caller's timed loop just ran the same (shapes, K) program, so the
    traced window executes warm — no compile lands in the timeline."""
    if os.environ.get("BENCH_KERNELS", "1") == "0":
        return []
    import tempfile

    td = tempfile.mkdtemp(prefix="bench_kernels_")
    try:
        mx.profiler.profiler_set_config(
            filename=os.path.join(td, "kernels.json"))
        mx.profiler.profiler_set_state("run")
        mod.train_window(batch, k, publish_grads=False).wait()
        trace = mx.profiler.dump_profile()
        return mx.telemetry.kernel_table(trace) if trace else []
    except Exception as e:
        print(f"kernel attribution skipped: {e}", file=sys.stderr)
        return []


def _resnet_train_flops(models, num_layers, image, batch_size):
    """Train FLOPs/img for the train/fit headline records (3x forward; at
    50 layers @224 this reproduces the 12.3 GFLOP/img the MFU field has
    used since PR-3, now computed rather than hardcoded)."""
    sym = models.resnet(num_classes=1000, num_layers=num_layers,
                        image_shape=",".join(map(str, image)))
    fwd = _fwd_flops(models, sym, data=(batch_size,) + image)
    return 3.0 * fwd if fwd else None


def _sweep_fit(mx, models, batch_size, image, dtype, num_layers, on_tpu,
               iters):
    """BENCH_SWEEP=1: grid-sweep (train_window K) x (dispatch depth) with
    short fit runs, adopt the best combo in the environment for the
    headline measurement, and return the per-combo rates so the BENCH
    trajectory records WHY the number moved."""
    ks = [int(x) for x in os.environ.get(
        "BENCH_SWEEP_K", "10,20,32" if on_tpu else "2,3").split(",")]
    depths = [int(x) for x in os.environ.get(
        "BENCH_SWEEP_DEPTH", "1,2,3" if on_tpu else "1,2").split(",")]
    results = []
    best = None
    for k in ks:
        for d in depths:
            os.environ["MXNET_TRAIN_WINDOW"] = str(k)
            os.environ["MXNET_DISPATCH_DEPTH"] = str(d)
            mod = _build_module(mx, models, batch_size, image, dtype,
                                num_layers, on_tpu)
            mx.telemetry.reset()
            rate, _spread, _cold = _run_fit_mode(
                mx, mod, batch_size, image, dtype, iters, 1)
            results.append(
                {"k": k, "depth": d, "img_per_sec": round(rate, 2)})
            if best is None or rate > best[0]:
                best = (rate, k, d)
    os.environ["MXNET_TRAIN_WINDOW"] = str(best[1])
    os.environ["MXNET_DISPATCH_DEPTH"] = str(best[2])
    print(f"sweep winner: K={best[1]} depth={best[2]} "
          f"({best[0]:.1f} img/s)", file=sys.stderr)
    return results


def _sweep_xla(mx, models, batch_size, image, dtype, num_layers, on_tpu,
               iters):
    """BENCH_SWEEP=xla: sweep MXNET_XLA_FLAGS candidates with short fit
    runs, adopt the fastest in the environment for the headline
    measurement, and return per-candidate rates so the trajectory records
    the choice. Candidates come from BENCH_SWEEP_XLA as ;-separated flag
    strings (each a comma-separated MXNET_XLA_FLAGS value; the empty
    string = compiler defaults). The flags feed both executable digests
    and the AOT fingerprint, so every candidate really recompiles — a
    candidate XLA rejects is recorded as an error, not a crash."""
    cands = os.environ.get(
        "BENCH_SWEEP_XLA",
        ";xla_latency_hiding_scheduler=true" if on_tpu
        else ";xla_cpu_enable_fast_math=true"
        ";xla_llvm_disable_expensive_passes=true").split(";")
    results = []
    best = None
    for flags in cands:
        os.environ["MXNET_XLA_FLAGS"] = flags
        mod = _build_module(mx, models, batch_size, image, dtype,
                            num_layers, on_tpu)
        mx.telemetry.reset()
        entry = {"xla_flags": flags}
        try:
            rate, _spread, _cold = _run_fit_mode(
                mx, mod, batch_size, image, dtype, iters, 1)
            entry["img_per_sec"] = round(rate, 2)
            if best is None or rate > best[0]:
                best = (rate, flags)
        except Exception as e:
            entry["error"] = f"{type(e).__name__}: {e}"[:200]
        results.append(entry)
    os.environ["MXNET_XLA_FLAGS"] = best[1] if best else ""
    print(f"xla sweep winner: {best[1] or '<defaults>'} "
          f"({best[0]:.1f} img/s)" if best else "xla sweep: no candidate ran",
          file=sys.stderr)
    return results


def _fit_phase_fields(record, snapshot):
    """dispatch_depth + steady-state fit.dispatch span share from the
    embedded telemetry snapshot — the JSON-tail fields the trajectory
    reads alongside train_window_k."""
    fit = snapshot.get("fit", {})

    def hsum(name):
        return (fit.get(name) or {}).get("sum", 0)

    total = sum(hsum(n) for n in (
        "dispatch", "data_wait", "metric", "callback", "window_wait"))
    if total:
        record["dispatch_span_share"] = round(hsum("dispatch") / total, 4)
    depth = (fit.get("dispatch_depth") or {}).get("value", 0)
    if depth:
        record["dispatch_depth"] = depth
    in_flight = (fit.get("windows_in_flight") or {}).get("max", 0)
    if in_flight:
        record["peak_windows_in_flight"] = in_flight


def _random_inference_params(mx, sym, image):
    """Random weights straight from shape inference — binding a training
    executor just to initialize would compile the whole train graph."""
    arg_shapes, _, aux_shapes = sym.infer_shape(
        data=(1,) + image, softmax_label=(1,))
    rng = np.random.RandomState(0)
    params = {}
    for n, s in zip(sym.list_arguments(), arg_shapes):
        if n in ("data", "softmax_label"):
            continue
        fan_in = int(np.prod(s[1:])) if len(s) > 1 else int(s[0])
        params[f"arg:{n}"] = mx.nd.array(
            (rng.randn(*s) * np.sqrt(2.0 / max(fan_in, 1)))
            .astype(np.float32))
    for n, s in zip(sym.list_auxiliary_states(), aux_shapes):
        params[f"aux:{n}"] = (mx.nd.ones(s) if "var" in n or "gamma" in n
                              else mx.nd.zeros(s))
    return params


def _drive_serve_phase(server, samples, clients, per_client, phase):
    """One concurrent-client phase against ``server``; returns
    [(ok, latency_s)] per request (the chaos leg needs per-phase
    availability and latency, not just aggregates)."""
    import threading

    results = []
    lock = threading.Lock()

    def client(cid):
        for i in range(per_client):
            tic = time.time()
            try:
                server.predict(samples[(cid + i) % len(samples)],
                               timeout=120)
                ok = True
            except Exception:  # noqa: BLE001 — availability accounting
                ok = False
            with lock:
                results.append((ok, time.time() - tic))

    threads = [threading.Thread(target=client, args=(c,), name=f"{phase}{c}")
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def _tp_annotated_mlp(mx, in_dim=64, hidden=256, num_classes=16):
    """Two-layer MLP with explicit column/row tensor-parallel shard
    annotations — the sharded serving legs' model (resnet carries no
    ``__shard__`` attributes; this is the canonical Megatron split)."""
    data = mx.sym.Variable("data")
    with mx.AttrScope(__shard__="tp:0"):
        w1 = mx.sym.Variable("fc1_weight")
    with mx.AttrScope(__shard__="tp:1"):
        w2 = mx.sym.Variable("fc2_weight")
    h = mx.sym.FullyConnected(data, weight=w1, num_hidden=hidden,
                              no_bias=True, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(h, weight=w2, num_hidden=num_classes,
                                 no_bias=True, name="fc2"), (in_dim,)


def _run_serve_sharded_legs(mx, clients, per_client):
    """``BENCH_SERVE_MESH_LEGS``: per-mesh-spec serving legs (``tp2``,
    ``pp2``, ``dp-tp2`` = every tp2 group as a dp replica) plus the
    group-replica scaling curve. Each leg reports throughput, p99 and the
    REQUEST-PATH compile count (must be 0 — the per-bucket sharded
    executables are all warmed up front)."""
    from mxnet_tpu.serving import ModelServer, ServingConfig

    sym, shape = _tp_annotated_mlp(mx)
    rng = np.random.RandomState(2)
    arg_shapes, _, _ = sym.infer_shape(data=(1,) + shape)
    params = {n: mx.nd.array(rng.uniform(-0.5, 0.5, s).astype(np.float32))
              for n, s in zip(sym.list_arguments(), arg_shapes)
              if n != "data"}
    samples = [rng.uniform(-1, 1, shape).astype(np.float32)
               for _ in range(16)]
    compile_ctr = mx.telemetry.counter("executor.jit_compile")

    def leg(mesh_spec, replicas):
        srv = ModelServer(
            sym, {k: v.copy() for k, v in params.items()},
            {"data": shape},
            config=ServingConfig(buckets="1,4", mesh=mesh_spec,
                                 replicas=replicas, fold_bn=False))
        srv.warmup()
        srv.start()
        srv.latency.reset()
        c0 = compile_ctr.value
        tic = time.time()
        results = _drive_serve_phase(srv, samples, clients, per_client,
                                     f"shard-{mesh_spec}-r{replicas}")
        wall = time.time() - tic
        out = {
            "img_per_sec": round(
                sum(1 for k, _ in results if k) / wall, 2),
            "errors": sum(1 for k, _ in results if not k),
            "replicas": len(srv.replicas),
            "p99_ms": round(srv.latency.percentile(99) / 1e3, 2),
            "request_path_compiles": compile_ctr.value - c0,
        }
        srv.close()
        return out

    legs_env = os.environ.get("BENCH_SERVE_MESH_LEGS", "tp2,pp2,dp-tp2")
    sharded = {}
    for name in [s.strip() for s in legs_env.split(",") if s.strip()]:
        if name.startswith("dp-"):
            # dp-of-<spec>: EVERY group serves (replicas=0 = all)
            sharded[name] = leg(name[3:], replicas=0)
        else:
            sharded[name] = leg(name, replicas=1)
    # group-replica scaling curve over the dp-of-tp2 layout: throughput
    # vs number of 2-device groups under the same concurrent load
    curve = {}
    for n in (1, 2, 4):
        curve[n] = leg("tp2", replicas=n)["img_per_sec"]
    sharded["tp2_scaling_curve"] = curve
    if curve[1] > 0:
        sharded["group_scaling_4x"] = round(curve[4] / curve[1], 3)
    return sharded


def _run_serve_chaos(mx, server, samples, clients, per_client):
    """BENCH_CHAOS=1: kill one replica under concurrent traffic (env
    fault injection, runtime-toggled), then revive it — report
    availability across pre/fault/recover phases and p99 DURING the
    fault. The serving availability SLO, measured, not asserted."""
    failover = mx.telemetry.counter("serving.replica.failover")
    f0 = failover.value
    pre = _drive_serve_phase(server, samples, clients, per_client, "pre")
    os.environ["MXNET_FI_SERVE_RAISE_REPLICA"] = "0"
    try:
        fault = _drive_serve_phase(server, samples, clients, per_client,
                                   "fault")
    finally:
        os.environ.pop("MXNET_FI_SERVE_RAISE_REPLICA", None)
    time.sleep(0.3)  # half-open probe backoff before the recovery phase
    recover = _drive_serve_phase(server, samples, clients, per_client,
                                 "recover")
    everything = pre + fault + recover
    ok = sum(1 for k, _ in everything if k)
    fault_lat = sorted(lat for _, lat in fault)
    p99_fault = fault_lat[max(0, int(len(fault_lat) * 0.99) - 1)] \
        if fault_lat else 0.0
    killed = next((r for r in server.stats()["replicas"] if r["id"] == 0),
                  {})
    return {
        "availability": round(ok / max(1, len(everything)), 4),
        "requests": len(everything),
        "failed": len(everything) - ok,
        "p99_during_fault_ms": round(p99_fault * 1e3, 2),
        "failover_count": failover.value - f0,
        "killed_replica_state": killed.get("state"),
    }


def _run_serve_mode(mx, models, image, num_layers, on_tpu):
    import threading

    from mxnet_tpu.serving import ModelServer, ServingConfig

    buckets = os.environ.get("BENCH_SERVE_BUCKETS",
                             "1,8,32" if on_tpu else "1,4,8")
    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", 8))
    per_client = int(os.environ.get("BENCH_SERVE_REQUESTS",
                                    50 if on_tpu else 25))
    seq_iters = int(os.environ.get("BENCH_SERVE_SEQ_ITERS",
                                   30 if on_tpu else 12))
    chaos = os.environ.get("BENCH_CHAOS") == "1"
    replicas_cfg = int(os.environ.get("BENCH_SERVE_REPLICAS", "0") or 0)
    if chaos and replicas_cfg == 0:
        replicas_cfg = 2  # chaos needs a survivor to fail over to

    sym = models.resnet(num_classes=1000, num_layers=num_layers,
                        image_shape=",".join(map(str, image)))
    params = _random_inference_params(mx, sym, image)

    def make_server(n_replicas):
        return ModelServer(
            sym, params, {"data": image},
            config=ServingConfig(buckets=buckets, replicas=n_replicas),
            dev_type="gpu" if on_tpu else "cpu")

    server = make_server(replicas_cfg)
    server.warmup()
    server.start()

    rng = np.random.RandomState(1)
    samples = [rng.uniform(-1, 1, image).astype(np.float32)
               for _ in range(16)]

    # sequential one-request-at-a-time reference through the server's own
    # smallest-bucket predictor — the exact program the batcher amortizes,
    # so the ratio isolates the batching win from model/compile
    # differences (bucket 1 when configured; otherwise one real sample
    # padded into the smallest bucket, which is what a lone request costs)
    b0 = server.config.buckets[0]
    p0 = server.predictor(b0)
    seq_batch = np.zeros((b0,) + image, np.float32)
    for s in samples[:2]:
        seq_batch[0] = s
        p0.run(data=seq_batch)  # warm
    tic = time.time()
    for i in range(seq_iters):
        seq_batch[0] = samples[i % len(samples)]
        p0.run(data=seq_batch)
    sequential = seq_iters / (time.time() - tic)

    mx.telemetry.reset()
    server.latency.reset()
    errors = []
    completed = [0] * clients

    def client(cid):
        for i in range(per_client):
            try:
                server.predict(samples[(cid + i) % len(samples)],
                               timeout=120)
                completed[cid] += 1
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    tic = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - tic
    total = sum(completed)
    snapshot = mx.telemetry.snapshot()
    lat = server.latency
    n_replicas = len(server.replicas)
    record = {
        "metric": f"resnet{num_layers}_serving_throughput"
                  + ("" if on_tpu else "_cpusmoke"),
        "value": round(total / wall, 2),
        "unit": "images/sec",
        "vs_baseline": round(total / wall / BASELINE_IMG_PER_SEC, 3),
        "sequential_img_per_sec": round(sequential, 2),
        "batching_speedup": round(total / wall / sequential, 3),
        "clients": clients,
        "requests": total,
        "errors": len(errors),
        "p50_ms": round(lat.percentile(50) / 1e3, 2),
        "p99_ms": round(lat.percentile(99) / 1e3, 2),
        "replicas": n_replicas,
        # per-replica batch counts over the SAME wall window: the
        # replication scaling evidence (a starved replica shows up as a
        # near-zero share, not as an invisible average)
        "per_replica_batches": {r["id"]: r["batches"]
                                for r in server.stats()["replicas"]},
        "telemetry": snapshot,
    }
    if n_replicas > 1 and os.environ.get("BENCH_SERVE_SCALING", "1") != "0":
        # the single-replica baseline under the SAME concurrent load:
        # the ratio is the replication win the trajectory tracks
        single = make_server(1)
        single.warmup()
        single.start()
        tic = time.time()
        results = _drive_serve_phase(single, samples, clients, per_client,
                                     "single")
        single_wall = time.time() - tic
        single.close()
        ok = sum(1 for k, _ in results if k)
        record["single_replica_img_per_sec"] = round(ok / single_wall, 2)
        if ok:
            record["replica_scaling"] = round(
                record["value"] / record["single_replica_img_per_sec"], 3)
    if os.environ.get("BENCH_SERVE_SHARDED") == "1":
        # tp/pp group-replica legs + scaling curve (needs a multi-device
        # mesh: real chips, or --xla_force_host_platform_device_count)
        record["sharded"] = _run_serve_sharded_legs(mx, clients,
                                                    per_client)
    if chaos:
        record["chaos"] = _run_serve_chaos(mx, server, samples, clients,
                                           per_client)
        record["availability"] = record["chaos"]["availability"]
        record["p99_during_fault_ms"] = \
            record["chaos"]["p99_during_fault_ms"]
    server.close()
    print(json.dumps(record))


def _ckpt_pass(mx, models, batch_size, image, dtype, num_layers, on_tpu,
               epochs, ckpt_dir, async_write):
    """One fit pass with per-epoch + mid-epoch saves; returns the
    per-save training-thread pause decomposition from telemetry."""
    mod = _build_module(mx, models, batch_size, image, dtype, num_layers,
                        on_tpu)
    rng = np.random.RandomState(0)
    n = batch_size * 4
    data = rng.uniform(-1, 1, (n,) + image).astype(mx.base.np_dtype(dtype))
    label = rng.randint(0, 1000, (n,)).astype(np.float32)
    train = mx.io.NDArrayIter(data, label, batch_size=batch_size,
                              last_batch_handle="discard")
    cfg = mx.CheckpointConfig(ckpt_dir, period=1, batch_period=2,
                              keep_n=2, async_write=async_write)
    saves0 = mx.telemetry.counter("checkpoint.save").value
    bytes0 = mx.telemetry.counter("checkpoint.bytes").value
    marks = {}
    for h in ("checkpoint.snapshot", "checkpoint.write",
              "checkpoint.write_async"):
        hist = mx.telemetry.histogram(h)
        marks[h] = (hist.count, hist.sum)
    t0 = time.time()
    mod.fit(train, num_epoch=epochs,
            optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
            checkpoint=cfg)
    wall_s = time.time() - t0
    saves = mx.telemetry.counter("checkpoint.save").value - saves0
    out = {"saves": saves, "wall_s": round(wall_s, 3),
           "bytes": mx.telemetry.counter("checkpoint.bytes").value - bytes0}
    for h, (c0, s0) in marks.items():
        hist = mx.telemetry.histogram(h)
        dc, ds = hist.count - c0, hist.sum - s0
        out[h.split(".", 1)[1] + "_us"] = round(ds / dc, 1) if dc else 0.0
    # the training thread stalls for snapshot always, plus the write only
    # when synchronous; async commits ride the writer thread
    out["pause_us"] = round(
        out["snapshot_us"] + (0.0 if async_write else out["write_us"]), 1)
    return out


def _run_ckpt_mode(mx, models, batch_size, image, dtype, num_layers,
                   on_tpu):
    """BENCH_MODE=ckpt: measure what a checkpoint save costs the training
    thread. Two identical fit passes with per-epoch + mid-epoch v2
    sharded saves — synchronous (pause = snapshot + write) then async
    (pause = snapshot only; the commit lands on the writer thread) — and
    report the per-save pause decomposition plus the async/sync ratio.
    The async pause bound is the elastic-checkpoint contract: growing
    model size moves write_us, not the training stall."""
    import shutil
    import tempfile

    epochs = int(os.environ.get("BENCH_CKPT_EPOCHS", 3))
    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        sync = _ckpt_pass(mx, models, batch_size, image, dtype, num_layers,
                          on_tpu, epochs, os.path.join(root, "sync"),
                          async_write=False)
        asy = _ckpt_pass(mx, models, batch_size, image, dtype, num_layers,
                         on_tpu, epochs, os.path.join(root, "async"),
                         async_write=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    record = {
        "metric": f"resnet{num_layers}_ckpt_pause"
                  + ("" if on_tpu else "_cpusmoke"),
        "value": asy["pause_us"],
        "unit": "us/save",
        "sync": sync,
        "async": asy,
        "async_vs_sync_pause": round(
            asy["pause_us"] / sync["pause_us"], 3) if sync["pause_us"]
        else None,
    }
    print(json.dumps(record))


# ---------------------------------------------------------------------------
# BENCH_MODE=suite — the whole-zoo scoreboard: every BASELINE workload
# (MLP/LeNet, ResNet-50, bucketed LSTM-PTB, SSD-VGG16, DCGAN) through the
# modern stack (fused K-step windows, pipelined dispatch, device metrics),
# each leg reporting train+infer samples/s, analytic GFLOPs/sample (MFU on
# TPU bf16), dtype, window K, dispatch depth and the STEADY-STATE compile
# count (executor.jit_compile + executor.fused_plan_compile over the timed
# region — the zero-recompile invariant, counter-verified).
#
# BENCH_MODE=score — the inference sweep over the 14 zoo symbols of the
# published perf table, sharing both the symbol list (models.SCORE_SYMBOLS)
# and the scoring loop with examples/benchmark_score.py.


def _suite_cfg(on_tpu):
    """(window K, dispatch depth, timed windows, warmup windows,
    infer iters) — BENCH_SUITE_* env-tunable, cpu-smoke-sized defaults."""
    return (
        max(1, int(os.environ.get("BENCH_SUITE_K", 16 if on_tpu else 2))),
        max(1, int(os.environ.get("BENCH_SUITE_DEPTH", 2))),
        max(1, int(os.environ.get("BENCH_SUITE_WINDOWS",
                                  8 if on_tpu else 2))),
        max(1, int(os.environ.get("BENCH_SUITE_WARMUP", 2))),
        max(1, int(os.environ.get("BENCH_SUITE_INFER_ITERS",
                                  20 if on_tpu else 3))),
    )


def _steady_compiles(mx):
    """Programs compiled since the last telemetry reset: AOTProgram builds
    (executor.jit_compile) + fused-window plan builds
    (executor.fused_plan_compile). The suite resets telemetry after warmup,
    so over a timed region this is the steady-state compile count — the
    acceptance invariant is that every workload pins it at 0."""
    tm = mx.telemetry
    return int(tm.counter("executor.jit_compile").value
               + tm.counter("executor.fused_plan_compile").value)


def _boundary_fence(boundary):
    """One-scalar device->host fetch off a WindowBoundary output: the only
    true execution barrier on every backend (block_until_ready can ack
    before remote execution completes on tunneled runtimes)."""
    if boundary is not None and boundary._outs:
        np.asarray(boundary._outs[0].ravel()[:1])


def _pipelined_windows(mx, dispatch, windows, depth, samples_per_window):
    """Time `windows` dispatches with `depth` windows in flight (the fit
    loop's backpressure discipline). Caller has already warmed up and
    fenced; telemetry is reset here so the compile count covers exactly
    the timed region. Returns (samples/sec, steady_compiles)."""
    from collections import deque

    mx.telemetry.reset()
    inflight = deque()
    last = None
    tic = time.time()
    for _ in range(windows):
        last = dispatch()
        inflight.append(last)
        while len(inflight) > depth:
            inflight.popleft().wait()
    while inflight:
        inflight.popleft().wait()
    _boundary_fence(last)
    dt = time.time() - tic
    # post-timing finiteness probe (one host fetch, outside the clock):
    # the bf16 recipes must train without NaN/Inf in the published outputs
    finite = True
    if last is not None and last._outs:
        finite = bool(np.all(np.isfinite(
            np.asarray(last._outs[0], dtype=np.float32))))
    return samples_per_window * windows / dt, _steady_compiles(mx), finite


def _forward_rate(mx, mod, batch, iters, warmup):
    """Forward-only samples/s with the benchmark_score dispatch/fence
    idiom (touch the output buffer to dispatch; fetch one scalar to
    fence). Returns (samples/sec, steady_compiles)."""
    def dispatch():
        mod.forward(batch, is_train=False)
        mod.get_outputs()[0]._data

    def fence():
        np.asarray(mod.get_outputs()[0]._data.ravel()[:1])

    for _ in range(max(1, warmup)):
        dispatch()
    fence()
    mx.telemetry.reset()
    tic = time.time()
    for _ in range(iters):
        dispatch()
    fence()
    rate = batch.data[0].shape[0] * iters / (time.time() - tic)
    return rate, _steady_compiles(mx)


def _workload_record(jax, on_tpu, train_rate, infer_rate, dtype, k, depth,
                     steady, fwd_flops, train_flops=None, finite=True):
    """One scoreboard row. ``steady`` is the train-leg steady-state compile
    count; ``train_flops`` defaults to 3x forward (fwd + input-grad +
    weight-grad), overridden by workloads whose step does more passes
    (DCGAN's three D passes)."""
    rec = {
        "train_samples_per_sec": round(train_rate, 2),
        "infer_samples_per_sec": round(infer_rate, 2),
        "dtype": dtype,
        "window_k": k,
        "dispatch_depth": depth,
        "steady_compiles": steady,
        "train_outputs_finite": finite,
    }
    if fwd_flops:
        # 6 decimals: the MLP head is ~1e-4 GFLOPs/sample and must not
        # round to a falsy 0.0
        rec["gflops_per_sample_fwd"] = round(fwd_flops / 1e9, 6)
        _maybe_mfu(rec, train_rate, jax, on_tpu, dtype,
                   train_flops or 3.0 * fwd_flops, key="mfu_train")
        _maybe_mfu(rec, infer_rate, jax, on_tpu, dtype, fwd_flops,
                   key="mfu_infer")
    return rec


def _train_leg(mx, mod, batch, k, depth, windows, warmup, samples_per_step):
    """Warm a Module's fused K-step window program, then time pipelined
    window dispatches. Returns (samples/sec, steady_compiles, finite)."""
    for _ in range(warmup):
        mod.train_window(batch, k, publish_grads=False).wait()
    _boundary_fence(mod.train_window(batch, k, publish_grads=False))
    return _pipelined_windows(
        mx, lambda: mod.train_window(batch, k, publish_grads=False),
        windows, depth, samples_per_step * k)


def _suite_classifier(mx, models, jax, on_tpu, sym, data_shape, num_classes,
                      dtype, cfg, init=None, optimizer_params=None,
                      kernels=False):
    """Shared train+infer legs for the single-input classifier-shaped
    workloads (MLP, LeNet, ResNet, SSD-train rides the same path with its
    own label plumbing — see _suite_ssd). ``kernels=True`` appends the
    top-10 per-kernel device-time table (one extra traced window after
    the timed legs)."""
    k, depth, windows, warmup, infer_iters = cfg
    bs = data_shape[0]
    ctx = mx.gpu() if on_tpu else mx.cpu()
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(data_shapes=[mx.io.DataDesc("data", data_shape, dtype)],
             label_shapes=[mx.io.DataDesc("softmax_label", (bs,))])
    mod.init_params(initializer=init or mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd", optimizer_params=optimizer_params or
                       {"learning_rate": 0.01, "momentum": 0.9})
    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.uniform(-1, 1, data_shape).astype(np.float32),
                       dtype=dtype)
    label = mx.nd.array(rng.randint(0, num_classes, (bs,)).astype(np.float32))
    batch = mx.io.DataBatch(data=[data], label=[label])
    train_rate, steady, finite = _train_leg(mx, mod, batch, k, depth,
                                            windows, warmup, bs)

    imod = mx.mod.Module(sym, context=ctx)
    imod.bind(data_shapes=[mx.io.DataDesc("data", data_shape, dtype)],
              for_training=False)
    imod.init_params(initializer=init or mx.init.Xavier())
    infer_rate, _ = _forward_rate(mx, imod, batch, infer_iters, warmup)
    fwd = _fwd_flops(models, sym, data=data_shape)
    rec = _workload_record(jax, on_tpu, train_rate, infer_rate, dtype, k,
                           depth, steady, fwd, finite=finite)
    if kernels:
        rec["kernels"] = _kernel_attribution(mx, mod, batch, k)
    return rec


def _suite_mlp(mx, models, jax, on_tpu, dtype, cfg):
    bs = 1024 if on_tpu else 64
    return _suite_classifier(mx, models, jax, on_tpu,
                             models.mlp(num_classes=10, dtype=dtype),
                             (bs, 784), 10, dtype, cfg)


def _suite_lenet(mx, models, jax, on_tpu, dtype, cfg):
    bs = 512 if on_tpu else 64
    return _suite_classifier(mx, models, jax, on_tpu,
                             models.lenet(num_classes=10, dtype=dtype),
                             (bs, 1, 28, 28), 10, dtype, cfg)


def _suite_resnet50(mx, models, jax, on_tpu, dtype, cfg):
    bs = 128 if on_tpu else 4
    image = (3, 224, 224) if on_tpu else (3, 64, 64)
    sym = models.resnet(num_classes=1000, num_layers=50,
                        image_shape=",".join(map(str, image)))
    return _suite_classifier(
        mx, models, jax, on_tpu, sym, (bs,) + image, 1000, dtype, cfg,
        init=mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                            magnitude=2), kernels=True)


def _suite_ssd(mx, models, jax, on_tpu, dtype, cfg):
    """SSD-VGG16: the multi-loss Group trains through the same fused
    window machinery as the classifiers (MultiBoxTarget in-graph, f32
    anchor math under the bf16 trunk recipe); the infer leg scores the
    detection symbol (SoftmaxActivation + in-graph NMS)."""
    k, depth, windows, warmup, infer_iters = cfg
    bs = 16 if on_tpu else 2
    size = 300 if on_tpu else 64
    num_classes = 20 if on_tpu else 3
    max_obj, obj_w = 4, 5  # ImageDetRecordIter layout: [cls,x1,y1,x2,y2]
    ctx = mx.gpu() if on_tpu else mx.cpu()
    net = models.ssd.get_symbol_train(num_classes=num_classes,
                                      data_shape=size, dtype=dtype)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=ctx)
    mod.bind(data_shapes=[mx.io.DataDesc("data", (bs, 3, size, size), dtype)],
             label_shapes=[mx.io.DataDesc("label", (bs, max_obj, obj_w))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.002,
                                         "momentum": 0.9, "wd": 5e-4})
    rng = np.random.RandomState(0)
    label = np.full((bs, max_obj, obj_w), -1.0, np.float32)
    for i in range(bs):
        for j in range(rng.randint(1, max_obj + 1)):
            x1, y1 = rng.uniform(0, 0.5, 2)
            w, h = rng.uniform(0.2, 0.5, 2)
            label[i, j] = [rng.randint(0, num_classes), x1, y1,
                           min(1.0, x1 + w), min(1.0, y1 + h)]
    data = mx.nd.array(
        rng.uniform(-1, 1, (bs, 3, size, size)).astype(np.float32),
        dtype=dtype)
    batch = mx.io.DataBatch(data=[data], label=[mx.nd.array(label)])
    train_rate, steady, finite = _train_leg(mx, mod, batch, k, depth,
                                            windows, warmup, bs)

    det = models.ssd.get_symbol(num_classes=num_classes, data_shape=size,
                                dtype=dtype)
    imod = mx.mod.Module(det, data_names=("data",), label_names=None,
                         context=ctx)
    imod.bind(data_shapes=[mx.io.DataDesc("data", (bs, 3, size, size),
                                          dtype)],
              for_training=False)
    imod.init_params(initializer=mx.init.Xavier())
    infer_rate, _ = _forward_rate(mx, imod, batch, infer_iters, warmup)
    fwd = _fwd_flops(models, net, data=(bs, 3, size, size),
                     label=(bs, max_obj, obj_w))
    return _workload_record(jax, on_tpu, train_rate, infer_rate, dtype, k,
                            depth, steady, fwd, finite=finite)


def _suite_lstm(mx, models, jax, on_tpu, dtype, cfg):
    """Bucketed LSTM-PTB: a materialized synthetic epoch chunks into
    K-batch windows through BucketingModule.train_window (grouped by
    bucket, one fused program per (bucket, group size) — after the warmup
    epoch every program is cached, so the timed epochs dispatch with zero
    compiles and zero per-batch host syncs). RNN legs run f32: the
    low-precision recipes cover the conv trunks, not the recurrent
    matmuls."""
    del dtype  # rnn leg is f32 by design; record says so explicitly
    k, depth, windows, warmup, _ = cfg
    bs = 32 if on_tpu else 8
    hidden = embed = 200 if on_tpu else 32
    vocab = 10000 if on_tpu else 100
    buckets = [16, 32] if on_tpu else [8, 16]
    rs = np.random.RandomState(0)
    sents = [[int(x) for x in rs.randint(1, vocab, int(rs.choice(buckets)))]
             for _ in range(bs * (8 if on_tpu else 4))]
    it = mx.rnn.BucketSentenceIter(sents, bs, buckets=buckets,
                                   invalid_label=0)
    sym_gen, state_names = models.lstm_lm_sym_gen(
        num_hidden=hidden, num_layers=2, num_embed=embed, vocab_size=vocab)
    ctx = mx.gpu() if on_tpu else mx.cpu()
    mod = mx.mod.BucketingModule(sym_gen=sym_gen,
                                 default_bucket_key=it.default_bucket_key,
                                 state_names=state_names, context=ctx)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier(factor_type="in",
                                               magnitude=2.34))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    # one materialized epoch, reused verbatim every timed pass: identical
    # chunking -> identical (bucket, group size) pairs -> pure cache picks
    batches = list(it)
    chunks = [batches[i:i + k] for i in range(0, len(batches), k)]
    for _ in range(warmup):
        for ch in chunks:
            mod.train_window(None, batches=ch, publish_grads=False).wait()

    from collections import deque

    mx.telemetry.reset()
    inflight = deque()
    last = None
    tic = time.time()
    for _ in range(windows):
        for ch in chunks:
            last = mod.train_window(None, batches=ch, publish_grads=False)
            inflight.append(last)
            while len(inflight) > depth:
                inflight.popleft().wait()
    while inflight:
        inflight.popleft().wait()
    _boundary_fence(last)
    dt = time.time() - tic
    train_rate = windows * len(batches) * bs / dt
    steady = _steady_compiles(mx)
    finite = bool(last is not None and last._outs and np.all(
        np.isfinite(np.asarray(last._outs[0], dtype=np.float32))))

    # infer: forward-only through the bound bucket programs (samples are
    # sequences); flops = bucket-length-weighted forward estimate
    fb = next(b for b in batches if b.bucket_key == it.default_bucket_key)
    for _ in range(2):
        mod.forward(fb, is_train=False)
        mod.get_outputs()[0]._data
    np.asarray(mod.get_outputs()[0]._data.ravel()[:1])
    tic = time.time()
    iters = max(1, 2 * len(batches))
    for _ in range(iters):
        mod.forward(fb, is_train=False)
        mod.get_outputs()[0]._data
    np.asarray(mod.get_outputs()[0]._data.ravel()[:1])
    infer_rate = bs * iters / (time.time() - tic)

    counts = {}
    for b in batches:
        counts[b.bucket_key] = counts.get(b.bucket_key, 0) + 1
    fwd, tot = 0.0, 0
    for length, c in counts.items():
        shapes = {"data": (bs, length), "softmax_label": (bs, length)}
        for sn in state_names:
            shapes[sn] = (bs, hidden)
        f = _fwd_flops(models, sym_gen(length)[0], **shapes)
        if f:
            fwd, tot = fwd + f * c, tot + c
    return _workload_record(jax, on_tpu, train_rate, infer_rate, "float32",
                            k, depth, steady, fwd / tot if tot else None,
                            finite=finite)


def _suite_dcgan(mx, models, jax, on_tpu, dtype, cfg):
    """DCGAN: the alternating G/D step is one fused device-resident
    program (GANModule.train_window, in-graph latent sampling). The record
    carries the reference imperative loop's rate too
    (legacy_train_samples_per_sec) so the fused-vs-legacy win is pinned in
    the scoreboard. Train cost/sample ≈ 3 G passes + 9 D passes (three D
    forwards, two with full backward, one for input grads); infer is pure
    G generation."""
    del dtype  # GAN leg is f32 (reference recipe); record says so
    k, depth, windows, warmup, infer_iters = cfg
    bs = 64 if on_tpu else 4
    z_dim = 100 if on_tpu else 16
    nf = 64 if on_tpu else 8
    ctx = mx.gpu() if on_tpu else mx.cpu()
    mx.random.seed(0)
    g_sym = models.dcgan_generator(ngf=nf, nc=3)
    d_sym = models.dcgan_discriminator(ndf=nf)
    gan = mx.mod.GANModule(g_sym, d_sym, context=ctx, batch_size=bs,
                           code_shape=(z_dim, 1, 1), data_shape=(3, 64, 64))
    gan.bind()
    gan.init_params()
    gan.init_optimizer()
    rng = np.random.RandomState(0)
    real = mx.nd.array(rng.rand(bs, 3, 64, 64).astype(np.float32) * 2 - 1)
    for _ in range(warmup):
        gan.train_window(real, k).wait()
    _boundary_fence(gan.train_window(real, k))
    train_rate, steady, finite = _pipelined_windows(
        mx, lambda: gan.train_window(real, k), windows, depth, bs * k)

    # reference imperative loop on the same per-window step count — its
    # rate is the fused path's acceptance floor. The boundary's outputs
    # are the PRE-update real-pass reads, so fencing them would leave the
    # trailing G/D updates untimed (the fused program can't cheat that
    # way: any output fetch forces the whole XLA call) — fence on the
    # updated weights instead.
    def weight_fence():
        for m in (gan.mod_g, gan.mod_d):
            exe = m._exec_group._exec
            name = next(iter(exe.arg_dict))
            np.asarray(exe.arg_dict[name]._data.ravel()[:1])

    gan._serial_window([real] * k, None)  # warm the serial-path programs
    weight_fence()
    tic = time.time()
    legacy_windows = max(1, windows // 2) if on_tpu else windows
    for _ in range(legacy_windows):
        gan._serial_window([real] * k, None)
    weight_fence()
    legacy_rate = bs * k * legacy_windows / (time.time() - tic)

    imod = mx.mod.Module(g_sym, data_names=("rand",), label_names=None,
                         context=ctx)
    imod.bind(data_shapes=[mx.io.DataDesc("rand", (bs, z_dim, 1, 1))],
              for_training=False)
    imod.init_params(initializer=mx.init.Normal(0.02))
    noise = mx.nd.random_normal(loc=0, scale=1, shape=(bs, z_dim, 1, 1))
    infer_rate, _ = _forward_rate(
        mx, imod, mx.io.DataBatch(data=[noise], label=[]), infer_iters, 2)

    g_fwd = _fwd_flops(models, g_sym, rand=(bs, z_dim, 1, 1))
    d_fwd = _fwd_flops(models, d_sym, data=(bs, 3, 64, 64), label=(bs,))
    train_flops = 3.0 * (g_fwd + 3.0 * d_fwd) if g_fwd and d_fwd else None
    rec = _workload_record(jax, on_tpu, train_rate, infer_rate, "float32",
                           k, depth, steady, g_fwd, train_flops=train_flops,
                           finite=finite)
    rec["legacy_train_samples_per_sec"] = round(legacy_rate, 2)
    rec["fused_speedup"] = round(train_rate / legacy_rate, 3)
    return rec


_SUITE_RUNNERS = (
    ("mlp", _suite_mlp),
    ("lenet", _suite_lenet),
    ("resnet-50", _suite_resnet50),
    ("lstm-ptb", _suite_lstm),
    ("ssd-vgg16", _suite_ssd),
    ("dcgan", _suite_dcgan),
)


def _run_suite_mode(mx, models, jax, on_tpu):
    """BENCH_MODE=suite: one JSON scoreboard covering every BASELINE
    workload; headline value is the geomean train samples/s (unit-hostile
    across workloads, but stable under proportional regressions — the
    bench_compare gate diffs the per-workload fields)."""
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16" if on_tpu else "float32")
    cfg = _suite_cfg(on_tpu)
    subset = os.environ.get("BENCH_SUITE_WORKLOADS")
    wanted = ([n.strip() for n in subset.split(",") if n.strip()]
              if subset else [n for n, _ in _SUITE_RUNNERS])
    runners = dict(_SUITE_RUNNERS)
    unknown = [n for n in wanted if n not in runners]
    if unknown:
        raise SystemExit(f"BENCH_SUITE_WORKLOADS: unknown {unknown}; "
                         f"have {[n for n, _ in _SUITE_RUNNERS]}")
    workloads = {}
    for name in wanted:
        print(f"suite: {name} ...", file=sys.stderr)
        workloads[name] = runners[name](mx, models, jax, on_tpu, dtype, cfg)
    rates = [w["train_samples_per_sec"] for w in workloads.values()]
    record = {
        "metric": "whole_zoo_suite" + ("" if on_tpu else "_cpusmoke"),
        "value": round(float(np.exp(np.mean(np.log(rates)))), 2),
        "unit": "geomean train samples/sec",
        "window_k": cfg[0],
        "dispatch_depth": cfg[1],
        "workloads": workloads,
    }
    _maybe_mesh(record, mx)
    _stamp_device_recipe(record, mx, models, on_tpu, dtype)
    print(json.dumps(record))


def _run_score_mode(mx, models, jax, on_tpu):
    """BENCH_MODE=score: the published-table inference sweep. The symbol
    list AND the scoring loop live in one place each (models.SCORE_SYMBOLS,
    examples/benchmark_score.score) so this mode cannot drift from the
    example. BENCH_SCORE_NETS subsets for cpu smoke."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "examples"))
    import benchmark_score

    subset = os.environ.get("BENCH_SCORE_NETS")
    networks = ([n.strip() for n in subset.split(",") if n.strip()]
                if subset else list(models.SCORE_SYMBOLS))
    bs = int(os.environ.get("BENCH_SCORE_BATCH", 32 if on_tpu else 2))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16" if on_tpu else "float32")
    iters = int(os.environ.get("BENCH_ITERS", 20 if on_tpu else 2))
    side = int(os.environ.get("BENCH_IMAGE", 224))
    image = (3, side, side)
    results = {}
    for net in networks:
        print(f"score: {net} ...", file=sys.stderr)
        rate = benchmark_score.score(net, bs, image, dtype, iters=iters,
                                     warmup=3 if on_tpu else 1)
        entry = {"samples_per_sec": round(rate, 2)}
        fwd = _fwd_flops(models, models.zoo.get_symbol(net),
                         data=(bs,) + image)
        if fwd:
            entry["gflops_per_sample_fwd"] = round(fwd / 1e9, 3)
            _maybe_mfu(entry, rate, jax, on_tpu, dtype, fwd)
        results[net] = entry
    rates = [e["samples_per_sec"] for e in results.values()]
    record = {
        "metric": "zoo_score_sweep" + ("" if on_tpu else "_cpusmoke"),
        "value": round(float(np.exp(np.mean(np.log(rates)))), 2),
        "unit": "geomean images/sec",
        "batch_size": bs,
        "dtype": dtype,
        "networks": results,
    }
    _maybe_mesh(record, mx)
    print(json.dumps(record))


# ---------------------------------------------------------------------------
# BENCH_MODE=io — the decode plane alone: img/s vs worker count. The
# scaling curve is the tentpole evidence that the parallel pool can feed
# the chip at device rate; serial (use_pool=0) is the baseline.
# ---------------------------------------------------------------------------
def _run_io_mode(mx, on_tpu):
    """BENCH_MODE=io: ImageRecordIter decode+augment throughput, serial
    vs pooled at 1/2/4/... workers, over a generated synthetic-JPEG .rec.
    Emits one JSON record: value = best pooled img/s, pool_speedup =
    best/serial (the gated ratio), scaling = the full curve."""
    import tempfile

    image = (3, 224, 224) if on_tpu else (3, 48, 48)
    batch_size = int(os.environ.get("BENCH_IO_BATCH", 32 if on_tpu else 16))
    records = int(os.environ.get("BENCH_IO_RECORDS",
                                 2048 if on_tpu else 320))
    passes = int(os.environ.get("BENCH_IO_PASSES", 2))
    workers = [int(w) for w in os.environ.get(
        "BENCH_IO_WORKERS", "1,2,4,8" if on_tpu else "1,2,4").split(",")]
    td = tempfile.mkdtemp(prefix="bench_io_")
    path = _write_bench_rec(mx, os.path.join(td, "bench.rec"), records, image)

    def rate(**kw):
        it = mx.io.ImageRecordIter(
            path_imgrec=path, data_shape=image, batch_size=batch_size,
            rand_crop=True, rand_mirror=True, shuffle=True, seed=0, **kw)
        for _ in it:       # warm epoch: readers, pool spin-up, page cache
            pass
        best = 0.0
        for _ in range(passes):
            it.reset()
            n, tic = 0, time.time()
            for _ in it:
                n += batch_size
            best = max(best, n / (time.time() - tic))
        it.close()
        return best

    mx.telemetry.reset()
    serial = rate(use_pool=False, preprocess_threads=1)
    scaling, best, best_workers = {}, 0.0, workers[0]
    for w in workers:
        r = rate(use_pool=True, preprocess_threads=w)
        scaling[str(w)] = round(r, 2)
        if r > best:
            best, best_workers = r, w
    from mxnet_tpu import native as _native

    record = {
        "metric": "io_plane_decode" + ("" if on_tpu else "_cpusmoke"),
        "value": round(best, 2),
        "unit": "images/sec",
        "serial_img_per_sec": round(serial, 2),
        "pool_speedup": round(best / serial, 3) if serial else 0.0,
        "workers_best": best_workers,
        "scaling": scaling,
        "records": records,
        "native_plane": bool(_native.available()),
        "cpu_count": os.cpu_count(),
        "telemetry": mx.telemetry.snapshot(),
    }
    print(json.dumps(record))


def main():
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import models

    on_tpu = jax.devices()[0].platform != "cpu"
    mode = os.environ.get("BENCH_MODE", "train")  # "train" | "fit"
    batch_size = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 8))
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16" if on_tpu else "float32")
    fused = max(1, int(os.environ.get("BENCH_FUSED_STEPS", 20 if on_tpu else 1)))
    warmup = 5 if on_tpu else 2
    iters = int(os.environ.get("BENCH_ITERS", 25 if on_tpu else 3))
    # iters counts STEPS; dispatches per timed window = ceil(iters/fused)
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", 4 if on_tpu else 1)))
    num_layers = int(os.environ.get("BENCH_LAYERS", 50))
    image = (3, 224, 224) if on_tpu else (3, 64, 64)

    if mode == "suite":
        _run_suite_mode(mx, models, jax, on_tpu)
        return

    if mode == "score":
        _run_score_mode(mx, models, jax, on_tpu)
        return

    if mode == "serve":
        _run_serve_mode(mx, models, image, num_layers, on_tpu)
        return

    if mode == "ckpt":
        _run_ckpt_mode(mx, models, batch_size, image, dtype, num_layers,
                       on_tpu)
        return

    if mode == "io":
        _run_io_mode(mx, on_tpu)
        return

    sweep = None
    if mode == "fit":
        # the real training loop defaults to the framework's intended
        # steady state on the chip: adaptive fused windows + pipelined
        # dispatch (the scheduler co-tunes K and depth from the probe).
        # CPU smoke keeps the env-driven default (tests opt in explicitly).
        if on_tpu:
            os.environ.setdefault("MXNET_TRAIN_WINDOW", "auto")
        if os.environ.get("BENCH_SWEEP") == "1":
            sweep = _sweep_fit(mx, models, batch_size, image, dtype,
                               num_layers, on_tpu, max(iters, 2))
        elif os.environ.get("BENCH_SWEEP") == "xla":
            sweep = _sweep_xla(mx, models, batch_size, image, dtype,
                               num_layers, on_tpu, max(iters, 2))

    mod = _build_module(mx, models, batch_size, image, dtype, num_layers,
                        on_tpu)

    if mode == "fit":
        # MXNET_TELEMETRY=1: record host spans + the jax device trace over
        # the fit epochs and write one merged Chrome/Perfetto timeline
        tracing = mx.telemetry.spans_enabled()
        if tracing:
            trace_out = os.environ.get("BENCH_TRACE_OUT", "bench_trace.json")
            mx.profiler.profiler_set_config(
                filename=os.path.splitext(trace_out)[0] + "_device.json")
            mx.profiler.profiler_set_state("run")
        # _run_fit_mode resets telemetry again at the first epoch boundary
        # so the snapshot covers the steady-state epochs only
        mx.telemetry.reset()
        fit_data = os.environ.get("BENCH_FIT_DATA", "synthetic")
        img_per_sec, spread, cold_compile_s = _run_fit_mode(
            mx, mod, batch_size, image, dtype, max(iters, 2), max(windows, 2),
            fit_data=fit_data)
        snapshot = mx.telemetry.snapshot()
        record = {
            "metric": f"resnet{num_layers}_fit_throughput"
                      + ("_recordio" if fit_data == "recordio" else "")
                      + ("" if on_tpu else "_cpusmoke"),
            "fit_data": fit_data,
            "value": round(img_per_sec, 2),
            "unit": "images/sec",
            "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
            "spread": round(spread, 4),
            "cold_compile_s": round(cold_compile_s, 3),
            "telemetry": snapshot,
        }
        _maybe_mfu(record, img_per_sec, jax, on_tpu, dtype,
                   _resnet_train_flops(models, num_layers, image, batch_size))
        _maybe_mesh(record, mx)
        _stamp_device_recipe(record, mx, models, on_tpu, dtype)
        window_k = mx.telemetry.gauge("fit.train_window_k").value
        if window_k:
            record["train_window_k"] = window_k
        _fit_phase_fields(record, snapshot)
        if sweep is not None:
            record["sweep"] = sweep
            if os.environ.get("BENCH_SWEEP") == "xla":
                # the adopted winner (what the headline number ran under)
                record["best_xla_flags"] = os.environ.get(
                    "MXNET_XLA_FLAGS", "")
        if tracing:
            device_trace = mx.profiler.dump_profile()  # stops the trace
            merged = mx.telemetry.merge_chrome_trace(
                mx.telemetry.events(), device_trace, trace_out)
            snap_path, prom_path = mx.telemetry.dump(
                os.environ.get("BENCH_TELEMETRY_OUT", "bench_telemetry.json"))
            record["trace"] = merged
            record["telemetry_snapshot"] = snap_path
            # attribute per-kernel device time straight off the merged
            # timeline the run already paid for
            record["kernels"] = mx.telemetry.kernel_table(merged)
            print(f"merged trace: {merged}  snapshot: {snap_path} "
                  f"{prom_path}", file=sys.stderr)
        if "kernels" not in record or not record["kernels"]:
            rng = np.random.RandomState(3)
            abatch = mx.io.DataBatch(
                data=[mx.nd.array(rng.uniform(-1, 1, (batch_size,) + image)
                                  .astype(np.float32), dtype=dtype)],
                label=[mx.nd.array(rng.randint(0, 1000, (batch_size,))
                                   .astype(np.float32))])
            record["kernels"] = _kernel_attribution(
                mx, mod, abatch, int(record.get("train_window_k") or 2))
        # AFTER the trace dump: the fresh module's recompile must not
        # pollute the steady-state timeline the trace documents
        if os.environ.get("BENCH_WARM_START", "1") != "0":
            record["warm_start_s"] = _time_warm_start(
                mx, models, batch_size, image, dtype, num_layers, on_tpu)
        print(json.dumps(record))
        return

    rng = np.random.RandomState(0)
    data = mx.nd.array(
        rng.uniform(-1, 1, (batch_size,) + image).astype(np.float32), dtype=dtype
    )
    label = mx.nd.array(rng.randint(0, 1000, (batch_size,)).astype(np.float32))
    batch = mx.io.DataBatch(data=[data], label=[label])

    def run_steps(n):
        # n train steps, dispatched as training windows of `fused` steps.
        # Windows run with lazy boundary publication (publish_grads=False):
        # nothing in this loop reads gradients, so the final step's f32
        # gradient materialization is dead-coded out of the program — the
        # same contract the pipelined fit loop uses. fence() still works:
        # outputs stay published.
        done = 0
        while done < n:
            k = min(fused, n - done)
            if k > 1:
                mod.train_window(batch, k, publish_grads=False)
            else:
                mod.forward_backward(batch)
                mod.update()
            done += k

    def fence():
        # a device->host fetch is the only true execution barrier on every
        # backend (block_until_ready can ack before remote execution
        # completes on tunneled runtimes); the last step's output depends
        # on the whole step chain, so one scalar fetch fences everything
        np.asarray(mod.get_outputs()[0]._data[0, :1])

    # warmup in whole windows too: a trailing partial window would compile
    # an extra program shape the timed region never uses; its duration is
    # where XLA compilation lives, reported as cold_compile_s
    tic = time.time()
    run_steps(((max(warmup, 2 * fused) + fused - 1) // fused) * fused)
    fence()
    cold_compile_s = round(time.time() - tic, 3)
    mx.telemetry.reset()  # snapshot covers the timed steady state only

    # several independently-timed windows: the reported value is the
    # median window, and the spread (max-min)/median is emitted so a
    # noisy tunnel/host can't silently swing the headline number
    # round steps up to whole windows: a partial window would compile a
    # second program shape for no measurement benefit
    iters = ((max(iters, fused) + fused - 1) // fused) * fused
    rates = []
    for _ in range(windows):
        tic = time.time()
        run_steps(iters)
        fence()
        rates.append(batch_size * iters / (time.time() - tic))
    import statistics

    rates.sort()
    img_per_sec = statistics.median(rates)
    spread = (rates[-1] - rates[0]) / img_per_sec if windows > 1 else 0.0
    record = {
        "metric": f"resnet{num_layers}_train_throughput"
                  + ("" if on_tpu else "_cpusmoke"),
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "spread": round(spread, 4),
        "cold_compile_s": cold_compile_s,
        "telemetry": mx.telemetry.snapshot(),
    }
    if os.environ.get("BENCH_WARM_START", "1") != "0":
        record["warm_start_s"] = _time_warm_start(
            mx, models, batch_size, image, dtype, num_layers, on_tpu,
            fused=fused)
    if os.environ.get("BENCH_GUARD", "1") != "0" and \
            not os.environ.get("MXNET_NONFINITE_GUARD"):
        # the non-finite sentinel's cost must stay visible: re-time the
        # same steady-state loop with MXNET_NONFINITE_GUARD=skip (one
        # extra all-finite reduce folded into the fused step — read per
        # fused call, so flipping the env here compiles the guarded
        # program and nothing else changes). Expected <2% delta.
        os.environ["MXNET_NONFINITE_GUARD"] = "skip"
        try:
            run_steps(2 * fused)  # compile + warm the guarded program
            fence()
            g_rates = []
            for _ in range(windows):
                tic = time.time()
                run_steps(iters)
                fence()
                g_rates.append(batch_size * iters / (time.time() - tic))
            guard_rate = statistics.median(g_rates)
        finally:
            del os.environ["MXNET_NONFINITE_GUARD"]
        record["guard_on_img_per_sec"] = round(guard_rate, 2)
        record["nonfinite_guard_overhead"] = round(
            1.0 - guard_rate / img_per_sec, 4)
    _maybe_mfu(record, img_per_sec, jax, on_tpu, dtype,
               _resnet_train_flops(models, num_layers, image, batch_size))
    _maybe_mesh(record, mx)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
